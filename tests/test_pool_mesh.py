"""Pool-axis mesh serving: sharded-scorer parity, mesh telemetry keys,
devices-aware placement, and the config seams that compose them.

The headline pin: every acquisition mode — fused select→reveal→mask
included — scores BIT-IDENTICALLY on a pool-axis mesh and on a single
device (row-local reductions never cross the sharded axis), for the
single-user family and the vmapped mesh × users fleet family alike.
Tier-1 keeps the 2-device parity sweep, the pure validation/placement
units, the (fn, width, n_devices) telemetry determinism and ONE
mesh-arm serve run pinning device-keyed compile events; the 4/8-device
sweep and the sharded-worker SIGKILL failover drill are ``slow`` and
run via ``scripts/mesh_check.sh``.
"""

import os

import numpy as np
import pytest

from consensus_entropy_tpu.obs import export, jit_telemetry
from consensus_entropy_tpu.ops import scoring
from consensus_entropy_tpu.parallel import pool_mesh
from consensus_entropy_tpu.parallel.pool_mesh import (
    make_pool_mesh_for,
    make_sharded_step_fns,
    match_partition_rules,
    sharded_fleet_fns_for_width,
    sharded_probs_buffer,
    sharded_scatter_rows,
)
from consensus_entropy_tpu.serve import FabricConfig, ServeConfig
from consensus_entropy_tpu.serve.placement import place, plan_failover

pytestmark = pytest.mark.mesh

#: single-user operand geometry for the parity sweeps — N divides every
#: mesh width the tests build (2, 4 and 8 of the harness's 8 virtual
#: devices)
M, N, C = 3, 16, 4

#: the single-user family keys (the ``*_masked`` variants exist only in
#: the vmapped fleet families)
_STEP_KEYS = tuple(k for k in pool_mesh._OPERANDS
                   if not k.endswith("_masked"))


def _operand_values(seed=11):
    """One coherent operand set covering every scorer's signature.
    Plain numpy — each call transfers fresh device buffers, so the
    donated fused arms never see a consumed input."""
    import jax

    rng = np.random.default_rng(seed)
    probs = rng.random((M, N, C)).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    hc_freq = rng.random((N, C)).astype(np.float32)
    hc_freq /= hc_freq.sum(-1, keepdims=True)
    hc_ent = (-np.sum(hc_freq * np.log(hc_freq), axis=-1)
              ).astype(np.float32)
    pool_mask = rng.random(N) < 0.8
    pool_mask[:4] = True  # always enough valid rows for top-k
    hc_mask = rng.random(N) < 0.8
    hc_mask[:4] = True
    return {"probs": probs, "pool_mask": pool_mask, "hc_freq": hc_freq,
            "hc_mask": hc_mask, "hc_ent": hc_ent,
            "weights": (rng.random(M) + 0.5).astype(np.float32),
            "key": jax.random.PRNGKey(3)}


def _args_for(fn_key, vals):
    return tuple(vals[op] for op in pool_mesh._OPERANDS[fn_key])


def _assert_results_equal(fn_key, got, want):
    for field, a, b in zip(want._fields, got, want):
        if b is None:
            assert a is None, (fn_key, field)
            continue
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"{fn_key}.{field} diverged under sharding")


def _step_parity(n_devices, k=2):
    """All modes, fused included: sharded vs single-device, bit-exact."""
    mesh = make_pool_mesh_for(n_devices)
    sharded = make_sharded_step_fns(mesh, k=k)
    base = scoring.make_scoring_fns(k=k)
    vals = _operand_values()
    for fn_key in _STEP_KEYS:
        got = sharded[fn_key](*_args_for(fn_key, vals))
        want = base[fn_key](*_args_for(fn_key, vals))
        _assert_results_equal(fn_key, got, want)


def _fleet_parity(n_devices, keys, k=2, users=2, width=N):
    """The mesh × users composition: stacked-bucket scorers sharded on
    the trailing pool axis vs the unsharded vmapped family."""
    from consensus_entropy_tpu.ops.scoring import (
        fleet_scoring_fns_for_width,
        stack_user_keys,
    )

    mesh = make_pool_mesh_for(n_devices)
    sharded = sharded_fleet_fns_for_width(mesh, k=k, width=width)
    base = fleet_scoring_fns_for_width(k=k, width=width)
    per_user = [_operand_values(seed=20 + u) for u in range(users)]
    stacked = {op: np.stack([vals[op] for vals in per_user])
               for op in ("probs", "pool_mask", "hc_freq", "hc_mask",
                          "hc_ent", "weights")}
    import jax

    stacked["key"] = stack_user_keys(
        [jax.random.PRNGKey(50 + u) for u in range(users)])
    stacked["member_mask"] = np.array([[True, True, False]] * users)
    for fn_key in keys:
        args = tuple(stacked[op]
                     for op in pool_mesh._OPERANDS[fn_key])
        _assert_results_equal(fn_key, sharded[fn_key](*args),
                              base[fn_key](*args))


# -- pure validation units -------------------------------------------------


def test_mesh_construction_and_partition_rule_validation():
    """Config-time errors surface as one clean message each: mesh bounds
    name the CI device-count knob, unmatched operands name themselves,
    and a non-dividing bucket width is rejected at family lookup."""
    with pytest.raises(ValueError, match="at least 1 device"):
        make_pool_mesh_for(0)
    with pytest.raises(ValueError,
                       match="xla_force_host_platform_device_count"):
        make_pool_mesh_for(64)
    assert make_pool_mesh_for(2).size == 2
    assert make_pool_mesh_for(2) is make_pool_mesh_for(2)  # cached
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(("probs", "bogus_operand"))
    mesh = make_pool_mesh_for(4)
    with pytest.raises(ValueError, match="does not divide across"):
        sharded_fleet_fns_for_width(mesh, k=2, width=10)
    # a mis-routed session still fails loudly at dispatch (the unsharded
    # family's width guard, plus the mesh spelling)
    fns = sharded_fleet_fns_for_width(make_pool_mesh_for(2), k=2,
                                      width=32)
    vals = _operand_values()
    with pytest.raises(ValueError, match="bucket routing error"):
        fns["mc"](np.stack([vals["probs"]] * 2),
                  np.stack([vals["pool_mask"]] * 2))


def test_serve_and_fabric_config_mesh_validation():
    """Mesh/composition flags fail at CONFIG CONSTRUCTION, not at first
    dispatch: device-count vs bucket-geometry mismatches and malformed
    per-host shapes each get a clean error."""
    with pytest.raises(ValueError, match="mesh_devices must be >= 1"):
        ServeConfig(mesh_devices=0)
    # the divisibility check runs on POST-ROUNDING widths (validate_
    # bucket_widths pads to a multiple of 8): (16, 24) stays (16, 24)
    # and 24 does not split 16 ways
    with pytest.raises(ValueError, match="do not divide"):
        ServeConfig(mesh_devices=16, bucket_widths=(16, 24))
    with pytest.raises(ValueError, match="power of\\s+two"):
        ServeConfig(mesh_devices=6)  # implicit pow2/planner geometry
    ServeConfig(mesh_devices=4, bucket_widths=(16, 32))
    ServeConfig(mesh_devices=4)
    with pytest.raises(ValueError, match="names 2 hosts but hosts=3"):
        FabricConfig(hosts=3, mesh_devices=(4, 1))
    with pytest.raises(ValueError, match="entry must be\\s+>= 1"):
        FabricConfig(hosts=2, mesh_devices=(4, 0))
    fc = FabricConfig(hosts=2, mesh_devices=(4, 1))
    assert fc.devices_for(0) == 4 and fc.devices_for(1) == 1
    assert fc.devices_for(5) == 1  # autoscaler scale-ups default 1 chip
    assert FabricConfig(hosts=2, mesh_devices=4).devices_for(7) == 4


def test_placement_devices_key_is_legacy_compatible_and_chip_aware():
    """Chips-per-host heterogeneity: a 4-chip worker attracts the
    wide-pool buckets — but ONLY when someone advertises >1 chip, and
    only behind co-location; with no (or all-1-chip) device info the
    PR 5 key is reproduced bit-for-bit."""
    loads = {"h0": 1, "h1": 1}
    empty = {"h0": {}, "h1": {}}
    # legacy identity: None, {}, and explicit 1-chip maps all agree
    for devices in (None, {}, {"h0": 1, "h1": 1}):
        assert place(32, loads=loads, buckets_by_host=empty,
                     devices=devices) == "h0"
    # the 4-chip host wins the wide bucket the id-tiebreak gave to h0
    assert place(32, loads=loads, buckets_by_host=empty,
                 devices={"h1": 4}) == "h1"
    # a non-dividing mesh would be a routing error at dispatch: the
    # 1-chip host (1 divides everything) outranks a 16-chip one for a
    # width-24 bucket
    assert place(24, loads=loads, buckets_by_host=empty,
                 devices={"h0": 1, "h1": 16}) == "h0"
    # co-location still dominates chips
    assert place(32, loads=loads,
                 buckets_by_host={"h0": {32: 2}, "h1": {}},
                 devices={"h1": 4}) == "h0"
    # plan_failover threads devices: both same-bucket victims land on
    # the wide survivor together
    from types import SimpleNamespace

    state = SimpleNamespace(assigned={}, pools={"a": 30, "b": 30})
    plan = plan_failover(["a", "b"], state=state, unresolved=[],
                         hosts=["h1", "h2"],
                         devices={"h1": 4, "h2": 1})
    assert plan == [("a", "h1"), ("b", "h1")]


# -- sharded parity --------------------------------------------------------


def test_sharded_step_parity_all_modes_two_devices():
    """THE acceptance pin (tier-1 case): all six acquisition modes —
    the FUSED select→reveal→mask graphs included, donation intact —
    score bit-identically on a 2-device pool mesh and on one device.
    Row-local reductions never cross the sharded axis, so this is exact
    equality, not allclose."""
    _step_parity(2)


def test_sharded_fleet_and_scatter_parity_two_devices():
    """The mesh × users composition and the sharded pool-state plumbing:
    stacked-bucket scorers (masked + fused + PRNG arms) match the
    unsharded vmapped family bit-for-bit, and the donated sharded
    scatter composes like the host-side update it replaces."""
    _fleet_parity(2, ("mc_fused", "mix_fused", "wmc_masked", "rand",
                      "hc_pre_fused"))
    mesh = make_pool_mesh_for(2)
    scatter = sharded_scatter_rows(mesh)
    buf = sharded_probs_buffer(mesh, M, N, C)
    rng = np.random.default_rng(5)
    p1 = rng.random((M, 3, C)).astype(np.float32)
    p2 = rng.random((M, 2, C)).astype(np.float32)
    # N (=16) is an OOB staging slot: dropped, like the host pad rows
    buf = scatter(buf, np.array([1, 5, N]), p1)
    buf = scatter(buf, np.array([5, 7]), p2)
    want = np.zeros((M, N, C), np.float32)
    want[:, [1, 5]] = p1[:, :2]
    want[:, [5, 7]] = p2
    np.testing.assert_array_equal(np.asarray(buf), want)


@pytest.mark.slow
@pytest.mark.parametrize("n_devices", [4, 8])
def test_sharded_parity_device_sweep(n_devices):
    """Acceptance: the same bit-exact parity holds across the mesh-width
    sweep (every width the 8-virtual-device harness can host), fleet
    family included — ``scripts/mesh_check.sh`` runs this leg."""
    _step_parity(n_devices)
    _fleet_parity(n_devices, tuple(pool_mesh._OPERANDS))


# -- (fn, width, n_devices) jit-family telemetry ---------------------------


def test_mesh_jit_families_keyed_and_deterministic_across_reset():
    """Mesh families land in telemetry keyed per (fn, width, n_devices)
    — and the family SET is a pure function of the lookups: after an
    in-process restart (``_reset_for_tests`` drops family state; the
    jit caches stay warm) the same lookups rebuild the identical label
    set with zero new builds."""
    events = []
    jit_telemetry.subscribe(events.append)
    try:
        # a distinctive k no other test builds mesh families for
        mesh = make_pool_mesh_for(2)
        make_sharded_step_fns(mesh, k=6)
        make_sharded_step_fns(mesh, k=6)
        sharded_fleet_fns_for_width(mesh, k=6, width=16)
    finally:
        jit_telemetry.unsubscribe(events.append)
    snap = jit_telemetry.snapshot()
    fam = snap["per_family"]["scoring:k6:fast/d2"]
    assert fam["builds"] == 1 and fam["lookups"] >= 2
    assert fam["hits"] == fam["lookups"] - 1
    assert snap["per_family"]["fleet:k6:fast@w16/d2"]["builds"] == 1
    assert {(e["fn"], e.get("width"), e.get("n_devices"))
            for e in events if e.get("phase") == "build"} \
        == {("scoring:k6:fast", None, 2), ("fleet:k6:fast", 16, 2)}
    mine = sorted(l for l in jit_telemetry.family_labels()
                  if ":k6:" in l and l.endswith("/d2"))
    assert mine == ["fleet:k6:fast@w16/d2", "scoring:k6:fast/d2"]
    # in-process restart: family state drops, the lru caches stay warm
    jit_telemetry._reset_for_tests()
    make_sharded_step_fns(mesh, k=6)
    sharded_fleet_fns_for_width(mesh, k=6, width=16)
    snap2 = jit_telemetry.snapshot()
    assert sorted(jit_telemetry.family_labels()) == mine
    for label in mine:
        assert snap2["per_family"][label]["builds"] == 0  # warm cache
        assert snap2["per_family"][label]["lookups"] == 1


@pytest.mark.serve
def test_serve_mesh_run_emits_device_keyed_compile_events(tmp_path):
    """A mesh-arm serve run: results match the unsharded geometry's
    ground truth, the scheduler's compile events carry the REAL device
    count, and a restarted run re-looks-up the same family set with no
    new builds (the satellite-4 determinism pin, mesh edition)."""
    from consensus_entropy_tpu.fleet import (
        FleetReport,
        FleetScheduler,
        FleetUser,
    )
    from consensus_entropy_tpu.serve import AdmissionJournal, FleetServer
    from tests.fabric_workload import make_cfg, make_committee, make_data

    cfg = make_cfg(mode="mc", epochs=2, queries=5)

    def serve_once(tag):
        report = FleetReport(str(tmp_path / f"metrics_{tag}.jsonl"))
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                               user_timings=False)
        server = FleetServer(
            sched, ServeConfig(target_live=2, mesh_devices=2),
            journal=AdmissionJournal(str(tmp_path / "journal.jsonl")))
        assert sched.mesh is not None and sched.mesh.size == 2
        entries = []
        for i in range(2):
            data = make_data(cfg.seed, f"u{i}", n_songs=30, mode="mc")
            ws = str(tmp_path / tag / f"u{i}")
            os.makedirs(ws)
            entries.append(FleetUser(
                data.user_id, make_committee(data, mode="mc"), data, ws,
                seed=cfg.seed))
        recs = server.serve(iter(entries))
        server.journal.close()
        report.close()
        assert all(r["error"] is None for r in recs)
        evs = export.read_jsonl_tolerant(
            str(tmp_path / f"metrics_{tag}.jsonl"))
        return [e for e in evs if e.get("event") == "compile"]

    first = serve_once("a")
    # the scheduler's one bucket built its mesh fleet family under the
    # real device count — and every event naming a mesh family says so
    built = {(e["fn"], e.get("width"), e.get("n_devices"))
             for e in first if e.get("phase") == "build"}
    assert ("fleet:k5:fast", 32, 2) in built
    fleet_evs = [e for e in first if e["fn"].startswith("fleet:")]
    assert fleet_evs and all(e.get("n_devices") == 2 for e in fleet_evs)
    assert "fleet:k5:fast@w32/d2" in jit_telemetry.family_labels()
    # restart: same journal dir, same users — the family set replays
    # exactly (no new builds; any xla events name a known family)
    again = serve_once("b")
    assert [e for e in again if e.get("phase") == "build"] == []
    assert {(e["fn"], e.get("width"), e.get("n_devices"))
            for e in again} \
        <= {(e["fn"], e.get("width"), e.get("n_devices")) for e in first}


# -- the sharded-worker failover drill -------------------------------------


@pytest.mark.slow
@pytest.mark.serve
@pytest.mark.faults
def test_mesh_worker_sigkill_fails_over_to_narrow_survivor(tmp_path):
    """Acceptance (``scripts/mesh_check.sh`` leg 2): a 2-host fabric
    whose h0 serves SHARDED over a 4-device pool mesh is SIGKILLed
    mid-iteration; its users fail over to the 1-chip survivor and
    finish with trajectories bit-identical to uninterrupted sequential
    runs — sharded partial progress resumes exactly on an unsharded
    host, because the sharded graphs are bit-equal, not merely close.
    The victim's chip count rode its heartbeat into the coordinator
    (the devices-aware placement feed)."""
    from consensus_entropy_tpu.fleet import FleetReport
    from consensus_entropy_tpu.serve import (
        AdmissionJournal,
        FabricCoordinator,
    )
    from tests.fabric_workload import (
        make_cfg,
        read_results,
        sequential_baselines,
        user_specs,
    )
    from tests.test_serve_fabric import (
        _kill_on_first_admit,
        _spawn_factory,
        _with_deadline,
    )

    cfg = make_cfg("mc", epochs=2)
    specs = user_specs(3)
    seq = sequential_baselines(str(tmp_path), cfg, specs)
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    journal = AdmissionJournal(jp)
    report = FleetReport()
    coord = FabricCoordinator(
        journal, fabric_dir,
        FabricConfig(hosts=2, lease_s=5.0, mesh_devices=(4, 1)),
        report=report,
        on_poll=_with_deadline(_kill_on_first_admit("h0")))
    spawn = _spawn_factory(
        fabric_dir, str(tmp_path), cfg, 3,
        env_extra={"h0": {"CETPU_MESH_DEVICES": "4"}})
    try:
        summary = coord.run([u for _, u, _ in specs], spawn)
    finally:
        journal.close()
    assert sorted(summary["finished"]) == [u for _, u, _ in specs]
    assert summary["failed"] == [] and summary["poisoned"] == []
    assert summary["revocations"] == 1
    assert summary["hosts"]["h0"] == "revoked"
    # the heartbeat advertised each host's chips before the kill
    assert coord.hosts["h0"].devices == 4
    assert coord.hosts["h1"].devices == 1
    results = read_results(fabric_dir)
    for _, uid, _ in specs:
        assert results[uid]["error"] is None
        assert results[uid]["result"]["trajectory"] \
            == seq[uid]["trajectory"]
        assert results[uid]["result"]["final_mean_f1"] \
            == seq[uid]["final_mean_f1"]
