"""Device-side GNB/SGD member inference vs sklearn, and the Committee's
device-slice scoring path vs its host path."""

import numpy as np
import pytest
from sklearn.linear_model import SGDClassifier
from sklearn.naive_bayes import GaussianNB

from consensus_entropy_tpu.models.committee import Committee, FramePool
from consensus_entropy_tpu.models.sklearn_members import (
    BoostedTreesMember,
    GNBMember,
    SGDMember,
)
from consensus_entropy_tpu.ops import device_members


@pytest.fixture
def problem(rng):
    X = rng.standard_normal((300, 12)).astype(np.float32)
    y = rng.integers(0, 4, 300)
    return X, y


def test_gnb_parity_with_sklearn(problem):
    X, y = problem
    est = GaussianNB().fit(X, y)
    got = np.asarray(device_members.gnb_probs(
        X, est.theta_.astype(np.float32), est.var_.astype(np.float32),
        np.log(est.class_prior_).astype(np.float32)))
    np.testing.assert_allclose(got, est.predict_proba(X), rtol=1e-3,
                               atol=1e-5)


def test_sgd_ova_parity_with_sklearn(problem):
    X, y = problem
    est = SGDClassifier(loss="log_loss", random_state=0).fit(X, y)
    got = np.asarray(device_members.ova_sigmoid_probs(
        X, est.coef_.astype(np.float32), est.intercept_.astype(np.float32)))
    np.testing.assert_allclose(got, est.predict_proba(X), rtol=1e-4,
                               atol=1e-6)


def test_segment_scorer_matches_pandas_groupby(rng, problem):
    import pandas as pd

    X, y = problem
    gnb = GaussianNB().fit(X, y)
    sgd = SGDClassifier(loss="log_loss", random_state=0).fit(X, y)
    seg = np.sort(rng.integers(0, 40, 300))
    scorer = device_members.make_device_committee_scorer(seg, 40)
    out = np.asarray(scorer(
        X,
        gnb.theta_[None].astype(np.float32),
        gnb.var_[None].astype(np.float32),
        np.log(gnb.class_prior_)[None].astype(np.float32),
        sgd.coef_[None].astype(np.float32),
        sgd.intercept_[None].astype(np.float32)))
    assert out.shape == (2, 40, 4)
    want_g = pd.DataFrame(gnb.predict_proba(X)).groupby(seg).mean().to_numpy()
    want_s = pd.DataFrame(sgd.predict_proba(X)).groupby(seg).mean().to_numpy()
    np.testing.assert_allclose(out[0], want_g, rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(out[1], want_s, rtol=1e-4, atol=1e-6)


def test_empty_member_slices(rng):
    X = rng.standard_normal((20, 5)).astype(np.float32)
    scorer = device_members.make_device_committee_scorer(
        np.repeat(np.arange(4), 5), 4)
    out = scorer(X,
                 np.zeros((0, 4, 5), np.float32),
                 np.zeros((0, 4, 5), np.float32),
                 np.zeros((0, 4), np.float32),
                 np.zeros((0, 4, 5), np.float32),
                 np.zeros((0, 4), np.float32))
    assert out.shape == (0, 4, 4)


def _fitted_committee(rng, X, y, device_members_flag):
    members = [GNBMember("gnb.it_0").fit(X, y),
               SGDMember("sgd.it_0", seed=0).fit(X, y),
               BoostedTreesMember("xgb.it_0", n_estimators=5, seed=0).fit(
                   X, y)]
    return Committee(members, [], device_members=device_members_flag)


def test_committee_device_path_matches_host_path(rng, problem):
    X, y = problem
    frame_song = np.repeat([f"s{i:02d}" for i in range(30)], 10)
    pool = FramePool(X, frame_song)
    y_by_song = y[::10]
    yf = np.repeat(y_by_song, 10)

    host_c = _fitted_committee(np.random.default_rng(0), X, yf, False)
    dev_c = _fitted_committee(np.random.default_rng(0), X, yf, True)

    songs = pool.song_ids[3:25]
    p_host = np.asarray(host_c.pool_probs(pool, None, songs, None))
    p_dev = np.asarray(dev_c.pool_probs(pool, None, songs, None))
    assert p_host.shape == p_dev.shape == (3, 22, 4)
    # member order preserved (gnb, sgd, xgb); numerics agree to f32
    np.testing.assert_allclose(p_dev, p_host, rtol=1e-3, atol=1e-5)
    # the scorer + device-resident features are cached on the pool itself
    cache = pool._ce_device_cache
    dev_c.pool_probs(pool, None, songs, None)
    assert pool._ce_device_cache is cache


def test_device_path_after_partial_fit(rng, problem):
    # Params are re-extracted each pass, so partial_fit updates must be
    # reflected without recompilation.
    X, y = problem
    frame_song = np.repeat(np.arange(30), 10)
    pool = FramePool(X, frame_song)
    yf = np.repeat(y[::10], 10)
    c = _fitted_committee(np.random.default_rng(0), X, yf, True)
    before = np.asarray(c.pool_probs(pool, None, pool.song_ids, None))
    c.update_host(X[:40], yf[:40])
    after = np.asarray(c.pool_probs(pool, None, pool.song_ids, None))
    assert not np.allclose(before[:2], after[:2])  # gnb+sgd moved
    # parity with the freshly-updated sklearn estimators
    for i, m in enumerate(c.host_members[:2]):
        want = pool.mean_by_song(m.estimator.predict_proba(pool.X))
        np.testing.assert_allclose(after[i], want, rtol=1e-3, atol=1e-5)
