"""Integration against the REAL DEAM dynamic-annotation CSVs.

This image mounts the reference's real `deam_annotations/{arousal,valence}.csv`
(1802 songs; per-song feature CSVs and audio are NOT mounted, so full
quality parity stays open — see ROUND4.md).  These tests feed the real
annotation rows — with their genuine NaN tails, per-song length mismatches
and sample-column grids — through our DEAM join, with synthetic feature
CSVs generated at each song's REAL timestamps.

Reference behavior being pinned: ``deam_classifier.py:58-104`` (join on the
shorter annotation row, frameTime∈sample-columns slice, DEAM quadrant
labeling).
"""

import os

import numpy as np
import pandas as pd
import pytest

from consensus_entropy_tpu.data import deam
from consensus_entropy_tpu.labels import quadrant_deam_np

REAL_DIR = "/root/reference/deam_annotations"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REAL_DIR),
    reason="real DEAM annotation CSVs not mounted in this image")


@pytest.fixture(scope="module")
def real_tables():
    return (pd.read_csv(os.path.join(REAL_DIR, "arousal.csv")),
            pd.read_csv(os.path.join(REAL_DIR, "valence.csv")))


def test_real_annotation_tables_shape(real_tables):
    arousal, valence = real_tables
    assert len(arousal) > 1500 and len(valence) > 1500
    assert arousal.columns[0] == "song_id"
    # the real grid starts at 15 s in 500 ms steps
    assert arousal.columns[1] == "sample_15000ms"
    secs = deam._sample_cols_to_seconds(arousal.columns[1:])
    assert secs[0] == 15.0
    assert np.allclose(np.diff(secs), 0.5)


def test_join_on_real_annotations(tmp_path, real_tables, rng):
    """Generate feature CSVs at a few real songs' timestamps and run the
    full loader; labels must match an independent quadrant computation on
    the raw annotation values."""
    arousal, valence = real_tables
    feat_dir = tmp_path / "features"
    feat_dir.mkdir()
    n_feat = 5
    cols = [f"f{i}" for i in range(n_feat)]
    picked = [int(s) for s in arousal.song_id.iloc[[0, 10, 200]]]
    for sid in picked:
        a_row = arousal[arousal.song_id == sid].dropna(axis=1)
        times = deam._sample_cols_to_seconds(a_row.columns[1:])
        df = pd.DataFrame(
            rng.standard_normal((len(times), n_feat)).astype(np.float32),
            columns=cols)
        df.insert(0, "frameTime", times)
        df.to_csv(feat_dir / f"{sid}.csv", sep=";", index=False)

    out = deam.load_dataset(str(feat_dir),
                            os.path.join(REAL_DIR, "arousal.csv"),
                            os.path.join(REAL_DIR, "valence.csv"))
    assert set(out.song_id.unique()) == set(picked)
    for sid in picked:
        sub = out[out.song_id == sid]
        a_row = arousal[arousal.song_id == sid].dropna(axis=1)
        v_row = valence[valence.song_id == sid].dropna(axis=1)
        # the loader keeps the SHORTER of the two annotation rows
        n_expect = min(len(a_row.columns), len(v_row.columns)) - 1
        assert len(sub) == n_expect
        # independent label oracle: hand-written DEAM quadrant geometry
        # (a>=0,v>=0 → Q1; a>=0,v<0 → Q2; a<0,v<0 → Q3; a<0,v>=0 → Q4),
        # NOT quadrant_deam_np — so a flipped boundary there can't cancel
        a = sub.arousal.to_numpy()
        v = sub.valence.to_numpy()
        want_q = np.where(
            a >= 0, np.where(v >= 0, "Q1", "Q2"),
            np.where(v < 0, "Q3", "Q4"))
        np.testing.assert_array_equal(sub.quadrants.to_numpy(), want_q)
        # the joined arousal values are exactly the raw row's leading slice
        np.testing.assert_allclose(
            sub.arousal.to_numpy(),
            a_row.iloc[0, 1: n_expect + 1].to_numpy(np.float64), rtol=1e-6)
