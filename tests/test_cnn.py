"""Flax ShortChunkCNN: architecture geometry, train/infer semantics, vmap
committee."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.models import short_cnn

TINY = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)


@pytest.fixture(scope="module")
def tiny_vars():
    return short_cnn.init_variables(jax.random.key(0), TINY)


def test_channel_widths_default():
    # short_cnn.py:304-310: 128,128,256,256,256,256,512
    assert CNNConfig().channel_widths == (128, 128, 256, 256, 256, 256, 512)


def test_output_shape_and_range(tiny_vars, rng):
    x = rng.standard_normal((3, TINY.input_length)).astype(np.float32)
    out = np.asarray(short_cnn.apply_infer(tiny_vars, x, TINY))
    assert out.shape == (3, 4)
    assert (out > 0).all() and (out < 1).all()  # sigmoid head


def test_jit_and_batch_size_one(tiny_vars, rng):
    # The AL loop evaluates with batch_size=1 (amg_test.py:378-387); BN must
    # use running stats so a single example is well-defined.
    x = rng.standard_normal((1, TINY.input_length)).astype(np.float32)
    f = jax.jit(lambda v, x: short_cnn.apply_infer(v, x, TINY))
    out = np.asarray(f(tiny_vars, x))
    assert out.shape == (1, 4)
    assert np.isfinite(out).all()


def test_train_updates_batch_stats(tiny_vars, rng):
    x = rng.standard_normal((4, TINY.input_length)).astype(np.float32)
    out, new_stats = short_cnn.apply_train(
        tiny_vars, x, jax.random.key(1), TINY)
    assert out.shape == (4, 4)
    old = jax.tree.leaves(tiny_vars["batch_stats"])
    new = jax.tree.leaves(new_stats)
    assert any(not np.allclose(a, b) for a, b in zip(old, new))


def test_dropout_only_in_train(tiny_vars, rng):
    x = rng.standard_normal((2, TINY.input_length)).astype(np.float32)
    a = short_cnn.apply_infer(tiny_vars, x, TINY)
    b = short_cnn.apply_infer(tiny_vars, x, TINY)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    t1, _ = short_cnn.apply_train(tiny_vars, x, jax.random.key(1), TINY)
    t2, _ = short_cnn.apply_train(tiny_vars, x, jax.random.key(2), TINY)
    assert not np.allclose(np.asarray(t1), np.asarray(t2))


def test_committee_vmap(tiny_vars, rng):
    members = [short_cnn.init_variables(jax.random.key(i), TINY)
               for i in range(3)]
    stacked = short_cnn.stack_params(members)
    assert short_cnn.num_members(stacked) == 3
    x = rng.standard_normal((5, TINY.input_length)).astype(np.float32)
    probs = np.asarray(short_cnn.committee_infer(stacked, x, TINY))
    assert probs.shape == (3, 5, 4)
    # members differ → outputs differ
    assert not np.allclose(probs[0], probs[1])
    # unstack round-trip matches per-member apply
    one = np.asarray(short_cnn.apply_infer(
        short_cnn.unstack_params(stacked, 1), x, TINY))
    np.testing.assert_allclose(probs[1], one, rtol=1e-5)


def test_param_count_matches_reference_architecture():
    # Independent arithmetic for the torch model (short_cnn.py:278-317):
    # conv k*k*cin*cout + cout bias; BN 2*c (scale/bias); dense in*out + out.
    cfg = CNNConfig()
    widths = cfg.channel_widths
    expect = 0
    cin = 1
    expect += 2 * 1  # spec_bn over 1 channel
    for w in widths:
        expect += 3 * 3 * cin * w + w  # conv
        expect += 2 * w  # bn scale+bias
        cin = w
    expect += 512 * 512 + 512  # dense1
    expect += 2 * 512  # head bn
    expect += 512 * 4 + 4  # dense2
    variables = short_cnn.init_variables(jax.random.key(0), cfg, batch_size=1)
    got = sum(int(np.prod(p.shape))
              for p in jax.tree.leaves(variables["params"]))
    assert got == expect


def test_spatial_collapse_geometry():
    # 128 mels / 231 frames through 7 2x2 pools → (1, 1) spatial, as the
    # reference's squeeze+MaxPool1d path requires (short_cnn.py:334-339).
    f, t = 128, 231
    for _ in range(7):
        f, t = f // 2, t // 2
    assert (f, t) == (1, 1)
