"""Mel frontend parity vs torch.stft and an independent mel-fb oracle.

torchaudio itself is not installed in this image; the oracles are built from
its documented semantics on top of ``torch.stft`` (the exact kernel
torchaudio's MelSpectrogram wraps).
"""

import numpy as np
import pytest
import torch

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.ops import mel


def _torch_power_spec(x_np, n_fft=512, hop=256):
    # torchaudio.transforms.Spectrogram defaults: centered, reflect pad,
    # periodic Hann, power=2, no normalization.
    x = torch.from_numpy(x_np.astype(np.float32))
    w = torch.hann_window(n_fft, periodic=True)
    spec = torch.stft(x, n_fft=n_fft, hop_length=hop, win_length=n_fft,
                      window=w, center=True, pad_mode="reflect",
                      return_complex=True)
    return (spec.abs() ** 2).numpy()


def _oracle_mel_fb(sr=16000, n_fft=512, n_mels=128, f_min=0.0, f_max=8000.0):
    # Independent implementation of torchaudio.functional.melscale_fbanks
    # (mel_scale='htk', norm=None), written loop-wise on purpose.
    n_freqs = n_fft // 2 + 1
    freqs = np.linspace(0, sr / 2, n_freqs)

    def to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    pts = to_hz(np.linspace(to_mel(f_min), to_mel(f_max), n_mels + 2))
    fb = np.zeros((n_freqs, n_mels))
    for m in range(n_mels):
        lo, ctr, hi = pts[m], pts[m + 1], pts[m + 2]
        for i, f in enumerate(freqs):
            if lo <= f <= ctr and ctr > lo:
                fb[i, m] = (f - lo) / (ctr - lo)
            elif ctr < f <= hi and hi > ctr:
                fb[i, m] = (hi - f) / (hi - ctr)
    return fb


def test_mel_filterbank_matches_oracle():
    fb = mel.mel_filterbank()
    oracle = _oracle_mel_fb()
    assert fb.shape == (257, 128)
    np.testing.assert_allclose(fb, oracle, atol=2e-6)


def test_filterbank_covers_band():
    fb = mel.mel_filterbank()
    # Low mel triangles can be narrower than one 31.25 Hz FFT bin and come
    # out all-zero — torchaudio does the same (it warns).  Above the first
    # few, every filter must have support.
    support = fb.sum(axis=0) > 0
    assert support[8:].all()


@pytest.mark.parametrize("method", ["matmul", "fft"])
def test_power_spectrogram_matches_torch_stft(rng, method):
    x = rng.standard_normal((2, 4096)).astype(np.float32)
    got = np.asarray(mel.power_spectrogram(x, method=method))
    want = _torch_power_spec(x)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=1e-2, rtol=1e-3)


def test_matmul_and_fft_paths_agree(rng):
    x = rng.standard_normal((8192,)).astype(np.float32)
    a = np.asarray(mel.power_spectrogram(x, method="matmul"))
    b = np.asarray(mel.power_spectrogram(x, method="fft"))
    np.testing.assert_allclose(a, b, atol=1e-2, rtol=1e-3)


def test_frame_count_canonical():
    cfg = CNNConfig()
    assert mel.n_frames_for(cfg.input_length) == 231
    x = np.zeros((1, cfg.input_length), dtype=np.float32)
    out = np.asarray(mel.log_mel_spectrogram(x, cfg))
    assert out.shape == (1, 128, 231)


def test_amplitude_to_db_semantics():
    p = np.array([1.0, 0.0, 1e-12, 100.0])
    db = np.asarray(mel.amplitude_to_db(p))
    np.testing.assert_allclose(db, [0.0, -100.0, -100.0, 20.0], atol=1e-4)


def test_log_mel_full_chain_vs_torch(rng):
    cfg = CNNConfig()
    x = rng.standard_normal((3, cfg.input_length)).astype(np.float32) * 0.1
    got = np.asarray(mel.log_mel_spectrogram(x, cfg))
    power = _torch_power_spec(x)  # (3, 257, 231)
    fb = _oracle_mel_fb()
    want = 10.0 * np.log10(np.maximum(
        np.einsum("bft,fm->bmt", power, fb), 1e-10))
    np.testing.assert_allclose(got, want, atol=5e-3)
