"""Quadrant geometry: exact parity with both reference mappings."""

import numpy as np
import pytest

from consensus_entropy_tpu import labels


def _ref_amg(a, v):
    # Oracle: the predicate chain at amg_test.py:69-78, re-expressed.
    if a >= 0 and v >= 0:
        return 0
    elif a > 0 and v < 0:
        return 1
    elif a <= 0 and v <= 0:
        return 2
    elif a < 0 and v > 0:
        return 3
    raise AssertionError("unreachable")


def _ref_deam(a, v):
    # Oracle: deam_classifier.py:90-97, re-expressed.
    if a >= 0 and v >= 0:
        return 0
    elif a >= 0 and v < 0:
        return 1
    elif a < 0 and v < 0:
        return 2
    elif a < 0 and v >= 0:
        return 3
    raise AssertionError("unreachable")


GRID = [-1.0, -0.5, 0.0, 0.5, 1.0]


@pytest.mark.parametrize("a", GRID)
@pytest.mark.parametrize("v", GRID)
def test_amg_matches_reference_predicates(a, v):
    assert int(labels.quadrant_amg(a, v)) == _ref_amg(a, v)
    assert int(labels.quadrant_amg_np(a, v)) == _ref_amg(a, v)


@pytest.mark.parametrize("a", GRID)
@pytest.mark.parametrize("v", GRID)
def test_deam_matches_reference_predicates(a, v):
    assert int(labels.quadrant_deam(a, v)) == _ref_deam(a, v)
    assert int(labels.quadrant_deam_np(a, v)) == _ref_deam(a, v)


def test_boundary_asymmetries_documented():
    # The two mappings genuinely disagree on the negative-valence arousal axis:
    # (a=0, v<0): AMG→Q3, DEAM→Q2.  (a<0, v=0): AMG→Q3, DEAM→Q4.
    assert int(labels.quadrant_amg(0.0, -1.0)) == 2
    assert int(labels.quadrant_deam(0.0, -1.0)) == 1
    assert int(labels.quadrant_amg(-1.0, 0.0)) == 2
    assert int(labels.quadrant_deam(-1.0, 0.0)) == 3


def test_vectorized_random(rng):
    a = rng.uniform(-2, 2, size=500)
    v = rng.uniform(-2, 2, size=500)
    expect_amg = np.array([_ref_amg(x, y) for x, y in zip(a, v)])
    expect_deam = np.array([_ref_deam(x, y) for x, y in zip(a, v)])
    np.testing.assert_array_equal(np.asarray(labels.quadrant_amg(a, v)), expect_amg)
    np.testing.assert_array_equal(labels.quadrant_amg_np(a, v), expect_amg)
    np.testing.assert_array_equal(np.asarray(labels.quadrant_deam(a, v)), expect_deam)
    np.testing.assert_array_equal(labels.quadrant_deam_np(a, v), expect_deam)


def test_one_hot_roundtrip(rng):
    c = rng.integers(0, 4, size=32)
    oh = labels.one_hot_np(c)
    assert oh.shape == (32, 4)
    np.testing.assert_array_equal(oh.argmax(axis=1), c)
    np.testing.assert_array_equal(np.asarray(labels.one_hot(c)), oh)


def test_name_codec():
    assert labels.class_to_name(0) == "Q1"
    np.testing.assert_array_equal(
        labels.names_to_classes(["Q1", "Q4", "Q2"]), [0, 3, 1])
