"""Entropy kernel: scipy.stats.entropy parity (the reference's scorer)."""

import jax
import jax.numpy as jnp
import numpy as np
from scipy.stats import entropy as scipy_entropy

from consensus_entropy_tpu.ops.entropy import masked_entropy, shannon_entropy


def test_matches_scipy_on_random_rows(rng):
    pk = rng.uniform(0.0, 1.0, size=(64, 4))
    got = np.asarray(shannon_entropy(pk, axis=1))
    want = scipy_entropy(pk, axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_matches_scipy_unnormalized_and_axis0(rng):
    pk = rng.uniform(0.0, 5.0, size=(4, 33))
    np.testing.assert_allclose(
        np.asarray(shannon_entropy(pk, axis=0)), scipy_entropy(pk, axis=0),
        rtol=1e-4)


def test_zero_entries_convention():
    # 0*log(0) = 0, exactly scipy's convention.
    pk = np.array([[0.5, 0.5, 0.0, 0.0], [1.0, 0.0, 0.0, 0.0]])
    got = np.asarray(shannon_entropy(pk, axis=1))
    np.testing.assert_allclose(got, [np.log(2.0), 0.0], atol=1e-5)


def test_uniform_is_log_c():
    pk = np.full((3, 4), 0.25)
    np.testing.assert_allclose(
        np.asarray(shannon_entropy(pk, axis=1)), np.log(4.0), rtol=1e-4)


def test_hc_rounding_parity(rng):
    # The HC table is built from frequencies rounded to 3 decimals
    # (amg_test.py:115); rows then no longer sum to exactly 1.  scipy
    # renormalizes — ours must too.
    counts = rng.integers(0, 20, size=(50, 4)) + 1
    freq = np.round(counts / counts.sum(axis=1, keepdims=True), 3)
    np.testing.assert_allclose(
        np.asarray(shannon_entropy(freq, axis=1)),
        scipy_entropy(freq, axis=1), rtol=1e-4)


def test_masked_entropy_fills_invalid(rng):
    pk = rng.uniform(0.1, 1.0, size=(8, 4))
    mask = np.array([True, False] * 4)
    ent = np.asarray(masked_entropy(pk, mask, axis=-1))
    assert np.all(np.isneginf(ent[~mask]))
    np.testing.assert_allclose(ent[mask], scipy_entropy(pk, axis=1)[mask],
                               rtol=1e-4)


def test_jit_and_grad():
    pk = jnp.asarray([[0.2, 0.3, 0.1, 0.4]])
    ent = jax.jit(shannon_entropy)(pk)
    np.testing.assert_allclose(np.asarray(ent), scipy_entropy(np.asarray(pk), axis=1),
                               rtol=1e-4)
    g = jax.grad(lambda p: shannon_entropy(p, axis=-1).sum())(pk)
    assert np.all(np.isfinite(np.asarray(g)))
