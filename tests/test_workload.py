"""workload/: the trace-driven load-generation subsystem + soak grader.

Tier-1 (un-marked) keeps the pure-host units — trace round-trip and
determinism pins, grammar validation, driver pacing/backpressure against
probe targets, the grader's torn-tail tolerance, the AdmissionQueue
``bound_reserve`` + clock-seam regressions, the cetpu-top history ring
and the coordinator admission-hold unit — plus ONE compressed-clock
FleetServer playback (2 users, 1 epoch).  The live-fabric churn drill
(worker subprocesses, disconnect/reconnect mid-run) is ``slow``-marked;
``scripts/soak_check.sh`` runs the full compressed-soak legs including
the coordinator-SIGKILL-mid-soak one.
"""

import json
import os
import threading

import pytest

from consensus_entropy_tpu.fleet import FleetReport
from consensus_entropy_tpu.obs.status import HistoryRing
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    AdmissionQueue,
    FabricConfig,
    FabricCoordinator,
    QueueClosed,
    QueueFull,
)
from consensus_entropy_tpu.workload import (
    DriverStats,
    TraceDriver,
    TraceSpec,
    deterministic_equal,
    generate,
    grade_run,
    load,
    percentile,
    save,
    spec_from_meta,
    trace_digest,
)
from consensus_entropy_tpu.workload import trace as trace_mod

pytestmark = pytest.mark.workload


# -- the trace model (pure, seeded) ----------------------------------------


def _spec(**kw):
    base = dict(seed=11, n_users=12, arrival="poisson", rate=6.0,
                churn_frac=0.25, pool_dist="bucket")
    base.update(kw)
    return TraceSpec(**base)


def test_trace_generate_is_deterministic_and_seed_sensitive():
    a, b = generate(_spec()), generate(_spec())
    assert a.events == b.events and a.meta == b.meta
    assert trace_digest(a) == trace_digest(b)
    assert trace_digest(generate(_spec(seed=12))) != trace_digest(a)


def test_trace_roundtrip_bit_identical(tmp_path):
    t = generate(_spec(arrival="mmpp", burst_dwell_s=0.5, horizon_s=30.0))
    p = str(tmp_path / "trace.jsonl")
    save(t, p)
    t2 = load(p)
    assert trace_mod.to_lines(t2) == trace_mod.to_lines(t)
    assert trace_digest(t2) == trace_digest(t)
    # the regeneration pin: header → spec → generate reproduces the file
    assert spec_from_meta(t2.meta) == _spec(arrival="mmpp",
                                            burst_dwell_s=0.5,
                                            horizon_s=30.0)
    assert trace_digest(generate(spec_from_meta(t2.meta))) \
        == trace_digest(t)
    # save → load → save is byte-stable
    p2 = str(tmp_path / "again.jsonl")
    save(t2, p2)
    assert open(p, "rb").read() == open(p2, "rb").read()


def test_trace_arrival_shapes_and_horizon():
    t = generate(_spec(churn_frac=0.0))
    arrives = [e["t"] for e in t.events if e["kind"] == "arrive"]
    assert len(arrives) == 12 and arrives == sorted(arrives)
    assert all(a >= 0 for a in arrives)
    # horizon pins the LAST arrival exactly
    th = generate(_spec(churn_frac=0.0, horizon_s=45.0))
    assert max(e["t"] for e in th.events) == pytest.approx(45.0, abs=1e-5)
    # replay plays the given offsets verbatim (sorted into event order)
    tr = generate(TraceSpec(seed=0, n_users=3, arrival="replay",
                            timestamps=(2.0, 0.5, 1.0)))
    assert [(e["t"], e["user"]) for e in tr.events] \
        == [(0.5, "u1"), (1.0, "u2"), (2.0, "u0")]
    # mmpp emits exactly n_users arrivals
    tm = generate(_spec(arrival="mmpp", churn_frac=0.0))
    assert len(tm.users) == 12


def test_trace_churn_events_pair_and_validate():
    t = generate(_spec(churn_frac=0.5, n_users=8))
    kinds = [e["kind"] for e in t.events]
    assert kinds.count("disconnect") == 4
    assert kinds.count("reconnect") == 4
    assert trace_mod.validate_records([t.meta] + t.events) == []
    # every disconnect follows its user's arrival and precedes the
    # reconnect (the grammar the validator enforces)
    seen: dict = {}
    for e in t.events:
        if e["kind"] == "arrive":
            seen[e["user"]] = "up"
        elif e["kind"] == "disconnect":
            assert seen[e["user"]] == "up"
            seen[e["user"]] = "away"
        else:
            assert seen[e["user"]] == "away"
            seen[e["user"]] = "up"


def test_trace_pool_dists():
    sizes = (12, 30, 60)
    cyc = generate(_spec(pool_dist="cycle", pool_sizes=sizes,
                         churn_frac=0.0))
    pools = [e["pool"] for e in cyc.events if e["kind"] == "arrive"]
    assert pools == [sizes[i % 3] for i in range(12)]
    skew = generate(_spec(pool_dist="skew", pool_sizes=sizes,
                          n_users=100, churn_frac=0.0))
    counts: dict = {}
    for e in skew.events:
        counts[e["pool"]] = counts.get(e["pool"], 0) + 1
    # the adversarial shape: one size dominates (~SKEW_FRAC of the mass)
    assert max(counts.values()) >= 60


def test_trace_spec_validation():
    with pytest.raises(ValueError):
        TraceSpec(n_users=0)
    with pytest.raises(ValueError):
        TraceSpec(arrival="burst")
    with pytest.raises(ValueError):
        TraceSpec(arrival="replay", n_users=2, timestamps=(0.0,))
    with pytest.raises(ValueError):
        TraceSpec(rate=0.0)
    with pytest.raises(ValueError):
        TraceSpec(churn_frac=1.5)
    with pytest.raises(ValueError):
        TraceSpec(pool_sizes=())
    with pytest.raises(ValueError):
        TraceSpec(class_mix=(("interactive", 0.0),))
    with pytest.raises(ValueError):
        TraceSpec(horizon_s=0.0)


def test_trace_record_validation_errors():
    head = {"schema": 1, "kind": "trace_header"}
    ok = {"kind": "arrive", "t": 0.5, "user": "u0",
          "cls": "batch", "pool": 8}
    assert trace_mod.validate_records([]) \
        == ["empty trace (no header line)"]
    assert any("trace_header" in e
               for e in trace_mod.validate_records([ok]))
    assert any("schema" in e for e in trace_mod.validate_records(
        [{"kind": "trace_header", "schema": 99}]))
    assert any("unknown event kind" in e
               for e in trace_mod.validate_records(
                   [head, {"kind": "leave", "t": 1.0, "user": "u0"}]))
    assert any("out of order" in e for e in trace_mod.validate_records(
        [head, dict(ok, t=2.0), dict(ok, t=1.0, user="u1")]))
    assert any("duplicate arrival" in e
               for e in trace_mod.validate_records(
                   [head, ok, dict(ok, t=1.0)]))
    assert any("reconnect without" in e
               for e in trace_mod.validate_records(
                   [head, ok, {"kind": "reconnect", "t": 1.0,
                               "user": "u0"}]))
    assert any("disconnect before arrival" in e
               for e in trace_mod.validate_records(
                   [head, {"kind": "disconnect", "t": 0.1,
                           "user": "zz"}]))
    assert any("positive int pool" in e
               for e in trace_mod.validate_records(
                   [head, dict(ok, pool=0)]))


def test_trace_load_rejects_invalid(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_bytes(b'{"kind": "arrive", "t": 1.0, "user": "u0"}\n')
    with pytest.raises(ValueError, match="trace_header"):
        load(str(p))


# -- the driver (probe targets, injected time) -----------------------------


class _FakeTime:
    """A virtual clock the driver's clock/sleep seam runs on: sleep()
    advances it instantly, so a 60 s trace plays in microseconds while
    the schedule stays measurable."""

    def __init__(self):
        self.t = 0.0

    def clock(self):
        return self.t

    def sleep(self, s):
        self.t += max(float(s), 0.0)


class _Probe:
    """Scriptable target: raise the queued exceptions per user first,
    then accept, recording (virtual time, verb, user)."""

    def __init__(self, ft, refuse=None):
        self.ft = ft
        self.refuse = dict(refuse or {})
        self.calls = []
        self.closed = False

    def submit(self, uid, *, cls, pool):
        left = self.refuse.get(uid)
        if left:
            self.refuse[uid] = left[1:]
            raise left[0]
        self.calls.append((round(self.ft.t, 6), "submit", uid, cls, pool))

    def disconnect(self, uid):
        self.calls.append((round(self.ft.t, 6), "disconnect", uid))

    def close(self):
        self.closed = True


def test_driver_plays_on_schedule_compressed():
    t = generate(TraceSpec(seed=3, n_users=4, arrival="replay",
                           timestamps=(0.0, 10.0, 20.0, 40.0),
                           pool_dist="cycle", pool_sizes=(8,)))
    ft = _FakeTime()
    probe = _Probe(ft)
    stats = TraceDriver(t, probe, time_scale=0.1, clock=ft.clock,
                        sleep=ft.sleep).run()
    assert [(c[0], c[2]) for c in probe.calls] \
        == [(0.0, "u0"), (1.0, "u1"), (2.0, "u2"), (4.0, "u3")]
    assert stats.submitted == 4 and stats.rejected == 0
    assert probe.closed  # close_on_exhaust


def test_driver_queue_full_backoff_no_busy_spin():
    t = generate(TraceSpec(seed=3, n_users=2, arrival="replay",
                           timestamps=(0.0, 0.0), pool_sizes=(8,)))
    ft = _FakeTime()
    probe = _Probe(ft, refuse={"u0": [QueueFull("x")] * 3})
    drv = TraceDriver(t, probe, clock=ft.clock, sleep=ft.sleep,
                      backoff_seed=7)
    stats = drv.run()
    assert stats.queue_full_retries == 3 and stats.submitted == 2
    # the backoff actually slept (jittered exponential — never a spin)
    assert ft.t > 0.0
    # replaying with the same backoff_seed backs off identically
    ft2 = _FakeTime()
    probe2 = _Probe(ft2, refuse={"u0": [QueueFull("x")] * 3})
    TraceDriver(t, probe2, clock=ft2.clock, sleep=ft2.sleep,
                backoff_seed=7).run()
    assert ft2.t == ft.t


def test_driver_terminal_refusal_kills_users_churn():
    t = generate(_spec(seed=5, n_users=4, churn_frac=1.0))
    victim = t.users[0]
    ft = _FakeTime()
    probe = _Probe(ft, refuse={victim: [QueueClosed("closed")]})
    stats = TraceDriver(t, probe, time_scale=0.01, clock=ft.clock,
                        sleep=ft.sleep).run()
    assert stats.rejected == 1
    # the dead user's disconnect/reconnect were skipped, not half-played
    assert stats.skipped == 2
    assert all(c[2] != victim for c in probe.calls)
    assert stats.disconnects == 3 and stats.reconnects == 3


def test_driver_max_retry_bound_and_stats_dict():
    t = generate(TraceSpec(seed=1, n_users=1, arrival="replay",
                           timestamps=(0.0,), pool_sizes=(8,)))
    ft = _FakeTime()
    probe = _Probe(ft, refuse={"u0": [QueueFull("x")] * 1000})
    stats = TraceDriver(t, probe, clock=ft.clock, sleep=ft.sleep,
                        max_retry_s=2.0).run()
    assert stats.rejected == 1 and stats.submitted == 0
    assert set(DriverStats().as_dict()) == set(stats.as_dict())
    with pytest.raises(ValueError):
        TraceDriver(t, probe, time_scale=0.0)


# -- the grader ------------------------------------------------------------


def test_percentile_nearest_rank():
    assert percentile([], 50) is None
    assert percentile([3.0], 99) == 3.0
    xs = list(range(1, 11))
    assert percentile(xs, 50) == 5
    assert percentile(xs, 95) == 10
    assert percentile(xs, 99) == 10
    assert percentile(xs, 0) == 1


def _grade_fixture(tmp_path, *, lose_u1=False, torn=True):
    """A miniature finished soak: 2-user trace, journal, one host's
    schema-v2 stream (optionally with a torn tail / a lost user)."""
    t = generate(TraceSpec(seed=2, n_users=2, arrival="replay",
                           timestamps=(0.0, 0.1),
                           class_mix=(("interactive", 1.0),),
                           pool_sizes=(8,)))
    users_dir = str(tmp_path / "users")
    os.makedirs(users_dir, exist_ok=True)
    jp = os.path.join(users_dir, "serve_journal.jsonl")
    j = AdmissionJournal(jp)
    for u in t.users:
        j.append("enqueue", u, cls="interactive")
        j.append("admit", u, host="h0")
    j.append("finish", t.users[0])
    if not lose_u1:
        j.append("finish", t.users[1])
    j.close()
    report = FleetReport(os.path.join(users_dir,
                                      "fleet_metrics_h0.jsonl"))
    for u in t.users:
        report.event("enqueue", user=u, depth=1)
    report.event("user_done", user=t.users[0])
    if not lose_u1:
        report.event("user_done", user=t.users[1])
    report.close()
    if torn:
        with open(os.path.join(users_dir, "fleet_metrics_h0.jsonl"),
                  "ab") as f:
            f.write(b'{"event": "user_do')  # the SIGKILL tail
    return t, users_dir, jp


def test_grader_torn_tail_and_determinism_pin(tmp_path):
    t, users_dir, jp = _grade_fixture(tmp_path)
    g = grade_run(users_dir, journal_path=jp, trace=t,
                  slo_s={"interactive": 60.0}, wall_s=2.0,
                  driver_stats={"submitted": 2})
    d = g["deterministic"]
    assert d["zero_loss"] and d["lost_users"] == []
    assert d["n_arrivals"] == 2 and d["finished"] == 2
    assert d["trace_sha"] == trace_digest(t)
    assert d["class_counts"] == {"interactive": 2}
    assert d["journal_ok"] and d["stream_ok"]
    row = g["measured"]["per_class"]["interactive"]
    assert row["n"] == 2 and row["within_slo"] is True
    assert g["measured"]["users_per_sec"] == pytest.approx(1.0)
    assert g["measured"]["driver"] == {"submitted": 2}
    # the pin: grading the same artifacts twice is bit-identical on the
    # deterministic section (json round-trip included)
    g2 = grade_run(users_dir, journal_path=jp, trace=t, wall_s=9.9)
    assert deterministic_equal(g, g2)
    assert deterministic_equal(json.loads(json.dumps(g)), g2)


def test_grader_flags_lost_users(tmp_path):
    t, users_dir, jp = _grade_fixture(tmp_path, lose_u1=True)
    g = grade_run(users_dir, journal_path=jp, trace=t)
    assert not g["deterministic"]["zero_loss"]
    assert g["deterministic"]["lost_users"] == [t.users[1]]
    g_ok = grade_run(_grade_fixture(tmp_path / "b")[1],
                     journal_path=_grade_fixture(tmp_path / "c")[2],
                     trace=t)
    assert not deterministic_equal(g, g_ok)


# -- AdmissionQueue: bound_reserve + clock seam (the satellite bugfix) -----


class _Entry:
    def __init__(self, uid, priority="batch"):
        self.user_id = uid
        self.priority = priority


def test_admission_queue_bound_reserve_stops_flood_starvation():
    """REGRESSION: without ``bound_reserve`` a never-stopping interactive
    producer fills the whole bound and batch producers see QueueFull
    forever — the aging guard never even gets a batch head to promote."""
    q = AdmissionQueue(4, bound_reserve={"batch": 2})
    q.put(_Entry("i0", "interactive"))
    q.put(_Entry("i1", "interactive"))
    with pytest.raises(QueueFull):
        q.put(_Entry("i2", "interactive"))  # batch's share is protected
    assert q.put(_Entry("b0")) == 3  # the starved class still admits
    assert q.put(_Entry("b1")) == 4
    with pytest.raises(QueueFull):
        q.put(_Entry("b2"))  # maxsize still binds everyone
    # covered reservations restrict nobody: draining batch reopens its
    # share, and interactive can then use genuinely free slots
    q.pop()  # i0 (strict priority)
    assert q.put(_Entry("i2", "interactive")) == 4
    with pytest.raises(ValueError):
        AdmissionQueue(2, bound_reserve={"batch": 2})


def test_admission_queue_clock_seam_drives_aging():
    fake = [0.0]
    q = AdmissionQueue(4, aging_s=5.0, clock=lambda: fake[0])
    q.put(_Entry("b0"))
    fake[0] = 1.0
    q.put(_Entry("i0", "interactive"))
    fake[0] = 4.0  # batch head has waited 4 s < aging_s
    assert q.pop()[0].user_id == "i0"
    q.put(_Entry("i1", "interactive"))
    fake[0] = 6.0  # batch head aged past 5 s: jumps strict priority
    assert q.head_waits()["batch"] == pytest.approx(6.0)
    assert q.pop()[0].user_id == "b0"
    assert q.pop()[0].user_id == "i1"


# -- the cetpu-top history ring --------------------------------------------


def _snap(host, t, **kw):
    return {"schema": 1, "kind": "status", "host": host, "t": t, **kw}


def test_history_ring_deltas_and_unchanged_skip():
    ring = HistoryRing(depth=3)
    assert ring.deltas("w0", ("live",)) == {}
    ring.push({"w0": _snap("w0", 1.0, live=2, queue_total=5)})
    ring.push({"w0": _snap("w0", 1.0, live=9)})  # unchanged t: skipped
    assert len(ring.history("w0")) == 1
    ring.push({"w0": _snap("w0", 2.0, live=3, queue_total=1)})
    d = ring.deltas("w0", ("live", "queue_total", "missing"))
    assert d == {"live": 1, "queue_total": -4, "span_s": 1.0}
    # depth bounds the window
    ring.push({"w0": _snap("w0", 3.0, live=4)})
    ring.push({"w0": _snap("w0", 4.0, live=8)})
    assert len(ring.history("w0")) == 3
    assert ring.history("w0")[0]["t"] == 2.0
    with pytest.raises(ValueError):
        HistoryRing(depth=1)


def test_top_render_delta_and_hold_lines():
    from consensus_entropy_tpu.cli import top

    ring = HistoryRing()
    snaps = {
        "fleet": _snap("fleet", 10.0, hosts={}, unresolved=9, queued=4,
                       in_flight=2, hold_active=True, holds=1, parked=2,
                       disconnects=3, reconnects=1),
        "w0": _snap("w0", 10.0, live=2, target_live=2, queue_total=6,
                    users_done=1, users_failed=0),
    }
    ring.push(snaps)
    out0 = top.render(snaps, now=10.5, ring=ring)
    assert "Δ" not in out0  # one snapshot: no movement measurable yet
    assert "ADMISSION HOLD (holds=1)" in out0
    assert "parked=2" in out0
    snaps2 = {
        "fleet": _snap("fleet", 12.0, hosts={}, unresolved=5, queued=1,
                       in_flight=2),
        "w0": _snap("w0", 12.0, live=2, target_live=2, queue_total=2,
                    users_done=4, users_failed=0),
    }
    ring.push(snaps2)
    out = top.render(snaps2, now=12.5, ring=ring)
    assert "Δ2s queued:-3 unresolved:-4" in out
    assert "Δ2s queue_total:-4 users_done:+3" in out
    # ring-less render (the --once path) stays delta-free
    assert "Δ" not in top.render(snaps2, now=12.5)


# -- the burn-rate admission hold (coordinator unit) -----------------------


def test_fabric_admission_hold_journals_and_defers_routing(tmp_path):
    fake = [100.0]
    jp = str(tmp_path / "j.jsonl")
    journal = AdmissionJournal(jp)
    cfg = FabricConfig(hosts=1, hold_on_burn=True, admission_hold_s=2.0,
                       slo_interactive_s=1.0, remedy_hold_s=3.0,
                       remedy_cooldown_s=30.0)
    coord = FabricCoordinator(journal, str(tmp_path), cfg,
                              clock=lambda: fake[0])
    # a sustained interactive burn: p95 over the rolling window far past
    # the 1 s SLO target
    for _ in range(10):
        coord._lat["interactive"].append(5.0)
    assert coord._class_p95s()["interactive"] == 5.0
    coord._pump_hold()  # arms the hysteresis timer
    assert coord.holds == 0 and coord._hold_until is None
    fake[0] += 2.0
    coord._pump_hold()  # 2 s < remedy_hold_s: still just hot
    assert coord.holds == 0
    fake[0] += 1.5
    coord._pump_hold()  # burned continuously past remedy_hold_s: act
    assert coord.holds == 1
    assert coord._hold_until == pytest.approx(fake[0] + 2.0)
    from consensus_entropy_tpu.resilience import io as dio
    with open(jp, "rb") as f:
        remedies = [dio.parse_frame(raw)[1] for raw in f
                    if b'"remedy"' in raw]
    assert len(remedies) == 1
    assert remedies[0]["action"] == "admission_hold"
    assert remedies[0]["cls"] == "interactive"
    evs = [e["event"] for e in coord.report.events]
    assert "admission_hold" in evs
    # arrivals during the hold journal immediately but route later
    coord._intake_open = True
    coord.submit("u7", cls="interactive", pool=8)
    coord._pump_intake()
    assert "u7" in coord._unresolved  # journaled + accounted
    assert coord._unrouted == ["u7"]  # routing deferred
    st = journal.state
    assert st.last.get("u7") == "enqueue"
    # one hold at a time; cooldown blocks an immediate re-fire
    for _ in range(10):
        coord._lat["interactive"].append(5.0)
    coord._pump_hold()
    assert coord.holds == 1
    journal.close()


def test_fabric_intake_backpressure_and_close(tmp_path):
    journal = AdmissionJournal(str(tmp_path / "j.jsonl"))
    coord = FabricCoordinator(journal, str(tmp_path),
                              FabricConfig(hosts=1, intake_max=2))
    with pytest.raises(QueueFull):
        coord.submit("u0")  # not open YET: retryable (the t=0 race —
        # a driver may start before run() opens the intake)
    coord._intake_open = True
    coord.submit("u0", cls="batch", pool=8)
    coord.submit("u1")
    with pytest.raises(QueueFull):
        coord.submit("u2")  # the bounded intake IS the backpressure
    coord.close_intake()
    with pytest.raises(QueueClosed):
        coord.submit("u3")
    assert coord._intake_live()  # parked ops still drain
    journal.close()


def test_fabric_config_soak_knob_validation():
    with pytest.raises(ValueError):
        FabricConfig(hosts=1, intake_max=0)
    with pytest.raises(ValueError):
        FabricConfig(hosts=1, admission_hold_s=0.0)
    with pytest.raises(ValueError):
        FabricConfig(hosts=1, slo_interactive_s=0.0)


# -- compressed playback against a real FleetServer ------------------------


def _server_fixture(tmp_path, n_users):
    from consensus_entropy_tpu.al import workspace
    from consensus_entropy_tpu.fleet import FleetScheduler, FleetUser
    from consensus_entropy_tpu.serve import FleetServer, ServeConfig
    from tests.test_fleet import _cfg, _committee, _user_data

    cfg = _cfg(mode="mc", epochs=1)
    specs = [(100 + i, f"u{i}", 20) for i in range(n_users)]
    from consensus_entropy_tpu.al.loop import ALLoop

    seq = {}
    for seed, uid, n in specs:
        data = _user_data(seed, uid, n_songs=n)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq[uid] = ALLoop(cfg).run_user(_committee(data), data, str(p))
    by = {uid: (seed, n) for seed, uid, n in specs}

    def build_entry(uid, cls, pool):
        seed, n = by[uid]
        data = _user_data(seed, uid, n_songs=n)
        fp = tmp_path / f"serve_{uid}"
        fp.mkdir(exist_ok=True)
        return FleetUser(
            uid, _committee(data), data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp)))

    sched = FleetScheduler(cfg, scoring_by_width=True)
    server = FleetServer(sched, ServeConfig(target_live=2,
                                            admit_window_s=0.02))
    return server, build_entry, seq, specs


def test_driver_plays_trace_into_fleet_server(tmp_path):
    """The tentpole end-to-end (tier-1 size): a seeded 2-user trace
    played through ServerTarget against a live FleetServer, compressed
    time — every user finishes with the sequential trajectory, and the
    producer stats account every arrival."""
    from consensus_entropy_tpu.workload import ServerTarget

    server, build_entry, seq, specs = _server_fixture(tmp_path, 2)
    t = generate(TraceSpec(
        seed=9, n_users=2, arrival="replay", timestamps=(0.0, 0.2),
        class_mix=(("interactive", 0.5), ("batch", 0.5)),
        pool_dist="cycle", pool_sizes=(20,)))
    driver = TraceDriver(t, ServerTarget(server, build_entry),
                         time_scale=0.05).start()
    done = {}
    try:
        server.serve((), on_result=lambda r: done.update(
            {r["user"]: r}), keep_open=True)
    finally:
        assert driver.join(timeout=30.0)
    assert driver.stats.submitted == 2 and driver.stats.rejected == 0
    for _, uid, _ in specs:
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] \
            == seq[uid]["trajectory"]


# -- the live-fabric churn drill (slow; scripts/soak_check.sh's leg 1) -----


@pytest.mark.slow
@pytest.mark.faults
def test_fabric_soak_churn_reconnect_bit_identical(tmp_path):
    """A keep-open fabric soak with mid-run churn: the trace disconnects
    a user (journaled evict, workspace kept) and reconnects it (journal
    re-admission, evict-ack gated); the run drains to zero loss and
    every user's trajectory is bit-identical to the uninterrupted
    sequential baseline."""
    import subprocess
    import sys

    from consensus_entropy_tpu.serve.hosts import fabric_paths
    from consensus_entropy_tpu.workload import FabricTarget
    from tests.fabric_workload import (
        make_cfg,
        read_results,
        sequential_baselines,
        user_specs,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "fabric_worker.py")
    n_users = 3
    cfg = make_cfg("mc", epochs=2)
    specs = user_specs(n_users)
    seq = sequential_baselines(str(tmp_path), cfg, specs)
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    journal = AdmissionJournal(jp)

    def spawn(host_id):
        log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
        env = {**os.environ, "PYTHONPATH": repo,
               "CETPU_FABRIC_METRICS": "1"}
        env.pop("CETPU_FAULTS", None)
        try:
            return subprocess.Popen(
                [sys.executable, worker, fabric_dir, host_id,
                 str(tmp_path), cfg.mode, str(cfg.epochs), str(n_users),
                 "5.0", "2"],
                stdout=log, stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()

    coord = FabricCoordinator(journal, fabric_dir,
                              FabricConfig(hosts=2, lease_s=5.0),
                              report=FleetReport())
    # u0 arrives, disconnects 1 (virtual) second later, reconnects 3 s
    # after that — mid-run for 2-epoch AL users under 0.5x compression
    t = trace_mod.Trace(
        meta={"schema": 1, "kind": "trace_header"},
        events=[
            {"kind": "arrive", "t": 0.0, "user": "u0",
             "cls": "batch", "pool": 30},
            {"kind": "arrive", "t": 0.2, "user": "u1",
             "cls": "batch", "pool": 30},
            {"kind": "arrive", "t": 0.4, "user": "u2",
             "cls": "batch", "pool": 30},
            {"kind": "disconnect", "t": 1.0, "user": "u0"},
            {"kind": "reconnect", "t": 4.0, "user": "u0"},
        ])
    driver = TraceDriver(t, FabricTarget(coord), time_scale=0.5).start()
    try:
        summary = coord.run([], spawn, keep_open=True)
    finally:
        assert driver.join(timeout=60.0)
        journal.close()
    assert sorted(summary["finished"]) == [u for _, u, _ in specs]
    assert summary["failed"] == [] and summary["poisoned"] == []
    assert summary["disconnects"] >= 1 and summary["reconnects"] >= 1
    results = read_results(fabric_dir)
    for _, uid, _ in specs:
        assert results[uid]["error"] is None
        assert results[uid]["result"]["trajectory"] \
            == seq[uid]["trajectory"]
    g = grade_run(fabric_dir, journal_path=jp)
    assert g["deterministic"]["zero_loss"]
    assert g["deterministic"]["journal_ok"]
    assert g["deterministic"]["stream_ok"]
