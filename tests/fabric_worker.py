"""Fabric worker subprocess entrypoint over the synthetic workload.

Spawned by ``tests/test_serve_fabric.py`` and ``bench.py --suite fabric``
(the production equivalent is the CLI's ``--fabric-worker`` re-exec):

    python tests/fabric_worker.py FABRIC_DIR HOST_ID WS_ROOT MODE \
        EPOCHS N_USERS LEASE_S TARGET_LIVE [SIZES_CSV]

Runs one ``FleetServer`` fed from the coordinator's assignment file
(``serve.hosts.run_worker``), persisting each finished user's result to
``FABRIC_DIR/results_<HOST_ID>.jsonl`` (append + fsync — the parity
assertions read these).  ``SIZES_CSV`` (optional) gives per-user pool
sizes — the skewed workload the elastic placement drills run.  Fault
rules arrive via the ``CETPU_FAULTS`` environment variable (installed
at package import), so chaos drills can wedge THIS worker's heartbeat
or kill its steps without touching its peers.  ``CETPU_FABRIC_METRICS=1``
writes this host's schema-v2 metrics stream + fleet summary to
``FABRIC_DIR/fleet_metrics_<HOST_ID>.jsonl`` (per-host stacked-dispatch
occupancy — what ``bench.py --suite elastic`` grades placement by).
``CETPU_MESH_DEVICES=K`` serves sharded over a K-device pool mesh (the
worker's heartbeat then advertises K chips — the mesh failover drill).
"""

import json
import os
import sys
import time


def main(argv) -> int:
    (fabric_dir, host_id, ws_root, mode, epochs, n_users, lease_s,
     target) = argv[:8]
    sizes = [int(x) for x in argv[8].split(",") if x] \
        if len(argv) > 8 and argv[8] else None
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tests.fabric_workload import (
        build_entry_factory,
        configure_jax,
        make_cfg,
        retrain_epochs_for,
        user_specs,
    )

    configure_jax()
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler
    from consensus_entropy_tpu.resilience.preemption import (
        EXIT_PREEMPTED,
        Preempted,
        PreemptionGuard,
    )
    from consensus_entropy_tpu.serve import ServeConfig
    from consensus_entropy_tpu.serve.hosts import run_worker

    cfg = make_cfg(mode=mode, epochs=int(epochs))
    specs = user_specs(int(n_users), sizes=sizes)
    results_path = os.path.join(fabric_dir, f"results_{host_id}.jsonl")

    def on_result(rec):
        line = {"user": str(rec["user"]), "error": rec["error"],
                "host": host_id, "t": round(time.time(), 3)}
        if rec["result"] is not None:
            line["result"] = {
                "trajectory": rec["result"]["trajectory"],
                "final_mean_f1": rec["result"]["final_mean_f1"]}
        with open(results_path, "ab") as f:
            f.write((json.dumps(line) + "\n").encode("utf-8"))
            f.flush()
            os.fsync(f.fileno())

    tracer = None
    if os.environ.get("CETPU_OBS_TRACE"):
        # the obs drill arm: span WAL exactly where the production CLI
        # worker puts it, run_id shared with the coordinator so a
        # failed-over user's trace id is continuous across hosts
        from consensus_entropy_tpu.obs.trace import Tracer
        from consensus_entropy_tpu.serve.hosts import fabric_paths

        tracer = Tracer(fabric_paths(fabric_dir, host_id)["spans"],
                        run_id=f"{cfg.mode}-{cfg.seed}", host=host_id)
    report = FleetReport(
        os.path.join(fabric_dir, f"fleet_metrics_{host_id}.jsonl")
        if os.environ.get("CETPU_FABRIC_METRICS") else None)
    scheduler = FleetScheduler(cfg, report=report,
                               retrain_epochs=retrain_epochs_for(mode),
                               scoring_by_width=True, tracer=tracer)
    status = alerts = None
    if os.environ.get("CETPU_OBS_STATUS"):
        # the live-introspection drill arm (scripts/obs_check.sh leg 2):
        # status snapshots into the named directory + the alert watcher
        # over this worker's own telemetry, exactly as the CLI wires them
        from consensus_entropy_tpu.obs.alerts import AlertWatcher
        from consensus_entropy_tpu.obs.status import StatusWriter

        status = StatusWriter(os.environ["CETPU_OBS_STATUS"], host_id,
                              interval_s=0.2)
        alerts = AlertWatcher(report)
    try:
        with PreemptionGuard() as guard:
            run_worker(fabric_dir, host_id,
                       build_entry=build_entry_factory(ws_root, cfg, specs),
                       scheduler=scheduler,
                       # planner_epoch=2: the tiny synthetic cohorts must
                       # still journal sketch epochs, or the elastic
                       # fleet planner would have nothing to merge
                       # CETPU_MESH_DEVICES=K: serve sharded — the
                       # server installs a K-device pool mesh and the
                       # heartbeat advertises the width (the failover
                       # drill kills a 4-chip worker into a 1-chip
                       # survivor); configure_jax() already forces 8
                       # virtual CPU devices, so K <= 8 always resolves
                       config=ServeConfig(
                           target_live=int(target), planner_epoch=2,
                           mesh_devices=int(os.environ.get(
                               "CETPU_MESH_DEVICES", 1)),
                           aging_s=float(os.environ.get(
                               "CETPU_OBS_AGING", 30.0))),
                       on_result=on_result, lease_s=float(lease_s),
                       preemption=guard, status=status, alerts=alerts)
    except Preempted:
        return EXIT_PREEMPTED
    finally:
        if tracer is not None:
            tracer.close()
        if report.jsonl_path is not None:
            # this host's per-bucket stacked-dispatch occupancy — the
            # elastic bench's placement metric (schema-v2 stream)
            report.write_summary(cohort=int(target))
            report.close()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
