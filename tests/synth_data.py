"""Shared builder for the miniature on-disk DEAM + AMG1608 layout used by
the CLI integration tests (single- and multi-process)."""

import numpy as np
import pandas as pd
from scipy.io import savemat

FEATURE_COLS = (["F0final_sma_stddev"] + [f"f{i}" for i in range(6)]
                + ["mfcc_sma_de[14]_amean"])

#: the newer openSMILE column vintage: the mfcc block carries a
#: ``pcm_fftMag_`` prefix (the real AMG1608 CSVs ship this layout; the
#: loaders dispatch on whichever stop column is present)
FEATURE_COLS_FFTMAG = (["F0final_sma_stddev"] + [f"f{i}" for i in range(6)]
                       + ["pcm_fftMag_mfcc_sma_de[14]_amean"])


def amg_dataset_frame(rng, *, n_songs: int = 1608, n_frames=(4, 8),
                      feature_cols=None) -> pd.DataFrame:
    """A real-shape AMG dataset cache table (the ``dataset_feats.csv`` the
    reference assembles, ``amg_test.py:57-60,128-144``): ``n_songs`` songs
    (default the true AMG1608 count) x several frames each, feature columns
    in either openSMILE vintage."""
    cols = FEATURE_COLS if feature_cols is None else feature_cols
    centers = rng.standard_normal((4, len(cols))) * 3.0
    rows, sids = [], []
    for i in range(n_songs):
        sid = 201 + i
        c = int(rng.integers(0, 4))
        k = int(rng.integers(*n_frames))
        rows.append(centers[c] + rng.standard_normal((k, len(cols))))
        sids += [sid] * k
    df = pd.DataFrame(np.vstack(rows).astype(np.float32), columns=cols)
    df.insert(0, "s_id", sids)
    return df


def build_synth_roots(tmp_path, rng) -> dict:
    """Class-separable synthetic DEAM + AMG1608 trees under ``tmp_path``."""
    centers = rng.standard_normal((4, len(FEATURE_COLS))) * 3.0

    # --- DEAM: features + dynamic annotations -------------------------
    deam = tmp_path / "deam"
    (deam / "features").mkdir(parents=True)
    (deam / "annotations").mkdir()
    times = np.arange(15.0, 25.0, 0.5)
    cols_ms = [f"sample_{int(t * 1000)}ms" for t in times]
    a_rows, v_rows = [], []
    for sid in range(1, 25):
        target = sid % 4  # song's dominant quadrant
        a_sign = 1.0 if target in (0, 1) else -1.0  # deam geometry
        v_sign = 1.0 if target in (0, 3) else -1.0
        a_vals = a_sign * rng.uniform(0.2, 1.0, len(times))
        v_vals = v_sign * rng.uniform(0.2, 1.0, len(times))
        feats = centers[target] + rng.standard_normal(
            (len(times), len(FEATURE_COLS))).astype(np.float32)
        df = pd.DataFrame(feats, columns=FEATURE_COLS)
        df.insert(0, "frameTime", times)
        df.to_csv(deam / "features" / f"{sid}.csv", sep=";", index=False)
        a_rows.append({"song_id": sid, **dict(zip(cols_ms, a_vals))})
        v_rows.append({"song_id": sid, **dict(zip(cols_ms, v_vals))})
    pd.DataFrame(a_rows).to_csv(deam / "annotations" / "arousal.csv",
                                index=False)
    pd.DataFrame(v_rows).to_csv(deam / "annotations" / "valence.csv",
                                index=False)

    # --- AMG: per-song feature csvs + .mat annotations ----------------
    amg = tmp_path / "amg1608"
    (amg / "feats").mkdir(parents=True)
    (amg / "anno").mkdir()
    n_songs, n_users = 40, 6
    song_ids = np.arange(201, 201 + n_songs)
    song_class = rng.integers(0, 4, size=n_songs)
    for sid, c in zip(song_ids, song_class):
        k = int(rng.integers(4, 8))
        feats = centers[c] + rng.standard_normal(
            (k, len(FEATURE_COLS))).astype(np.float32)
        df = pd.DataFrame(feats, columns=FEATURE_COLS)
        df.insert(0, "frameTime", np.arange(k) * 1.0)
        df.to_csv(amg / "feats" / f"{sid}.csv", sep=";", index=False)
    # annotations: valence/arousal consistent with each song's class (amg
    # geometry, [valence, arousal] order), light per-user noise on magnitude
    lab = np.full((n_songs, n_users, 2), np.nan)
    for i, c in enumerate(song_class):
        a_sign = 1.0 if c in (0, 1) else -1.0
        v_sign = 1.0 if c in (0, 3) else -1.0
        for u in range(n_users):
            if rng.uniform() < 0.9:  # most users annotated most songs
                lab[i, u, 0] = v_sign * rng.uniform(0.3, 1.0)
                lab[i, u, 1] = a_sign * rng.uniform(0.3, 1.0)
    savemat(str(amg / "anno" / "AMG1608.mat"), {"song_label": lab})
    savemat(str(amg / "anno" / "1608_song_id.mat"),
            {"mat_id2song_id": song_ids.reshape(-1, 1)})

    models = tmp_path / "models"
    return {"deam": str(deam), "amg": str(amg), "models": str(models)}
