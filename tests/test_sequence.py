"""Sequence-parallel full-song scoring vs the single-device window oracle,
on a real 8-way virtual-CPU mesh (conftest.py) — the same GSPMD/halo code
path a TPU slice runs, minus ICI."""

import jax
import numpy as np
import pytest

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.models.short_cnn import init_variables, stack_params
from consensus_entropy_tpu.parallel import sequence
from consensus_entropy_tpu.parallel.mesh import make_seq_mesh

TINY = CNNConfig(n_channels=4, n_fft=64, hop_length=32, n_mels=16,
                 n_layers=2, input_length=1024)


@pytest.fixture(scope="module")
def committee():
    members = [init_variables(jax.random.key(i), TINY, batch_size=2)
               for i in range(2)]
    return stack_params(members)


def _song(rng, n):
    return (rng.standard_normal(n) * 0.05).astype(np.float32)


def test_plan_geometry():
    p = sequence.plan_windows(10_000, 8, window=1024, hop=1024)
    assert p.n_windows == 9  # floor((10000-1024)/1024)+1
    assert p.windows_per_shard == 2 and p.halo == 0
    assert p.padded_len == 8 * 2 * 1024

    p = sequence.plan_windows(10_000, 8, window=1024, hop=512)
    assert p.n_windows == (10_000 - 1024) // 512 + 1 == 18
    assert p.halo == 512
    assert p.padded_len == 8 * p.windows_per_shard * 512 + 512

    short = sequence.plan_windows(100, 8, window=1024, hop=1024)
    assert short.n_windows == 1


def test_plan_rejects_bad_hop():
    with pytest.raises(ValueError):
        sequence.plan_windows(5000, 4, window=1024, hop=2048)


@pytest.mark.parametrize("n_samples,hop", [
    (16 * 1024, 1024),      # exact tiling, no halo
    (10_000, 1024),         # ragged tail, no halo
    (10_000, 512),          # 50% overlap -> ppermute halo exchange
    (7_000, 300),           # non-divisor hop, halo
    (500, 1024),            # shorter than one window
])
def test_sharded_matches_oracle(rng, committee, n_samples, hop):
    mesh = make_seq_mesh()
    wave = _song(rng, n_samples)
    plan = sequence.plan_windows(n_samples, mesh.shape["seq"],
                                 window=TINY.input_length, hop=hop)
    scorer = sequence.make_full_song_scorer(mesh, plan, TINY)
    got = scorer(committee, jax.numpy.asarray(sequence.pad_song(wave, plan)))
    want = sequence.full_song_probs_reference(committee, wave, plan, TINY)
    assert got.shape == (2, TINY.n_class)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_scorer_validates_mesh_and_window(committee):
    mesh = make_seq_mesh()
    plan = sequence.plan_windows(8192, 4, window=1024)
    with pytest.raises(ValueError):
        sequence.make_full_song_scorer(mesh, plan, TINY)  # 4 != 8 shards
    plan8 = sequence.plan_windows(8192, 8, window=512)
    with pytest.raises(ValueError):
        sequence.make_full_song_scorer(mesh, plan8, TINY)  # window mismatch


def test_plan_rejects_halo_deeper_than_chunk():
    # 75% overlap on a short song / wide mesh would need a multi-hop halo;
    # plan_windows must reject it with a clear error, not crash at trace.
    with pytest.raises(ValueError, match="overlap"):
        sequence.plan_windows(2816, 8, window=1024, hop=256)
    # Same overlap on a long song is fine (chunk covers the halo).
    p = sequence.plan_windows(200_000, 8, window=1024, hop=256)
    assert p.halo <= p.chunk_len


def test_committee_predict_song_sequence(rng):
    """The production Committee surface for long audio: sequence-parallel
    scoring matches the single-device window oracle, and repeat calls with
    the same geometry reuse one compiled scorer."""
    from consensus_entropy_tpu.models.committee import CNNMember, Committee

    members = [CNNMember(f"it_{i}",
                         init_variables(jax.random.key(i), TINY,
                                        batch_size=2), TINY)
               for i in range(2)]
    c = Committee([], members, TINY, full_song_hop=512)
    mesh = make_seq_mesh()
    wave = _song(rng, 50_000)  # ~49x the window length
    got = np.asarray(c.predict_song_sequence(wave, mesh))
    assert got.shape == (2, 4)
    plan = sequence.plan_windows(len(wave), 8, window=1024, hop=512)
    want = np.asarray(sequence.full_song_probs_reference(
        c._stacked(), wave, plan, TINY))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    # compiled-scorer cache: keyed by geometry bucket + mesh VALUE, with
    # n_windows a dynamic operand — a different length in the same
    # windows-per-shard bucket and a freshly built (equal) mesh both reuse
    # the entry; only a new bucket compiles another program
    assert len(c._seq_scorers) == 1
    c.predict_song_sequence(_song(rng, 49_000), make_seq_mesh())
    assert len(c._seq_scorers) == 1
    c.predict_song_sequence(_song(rng, 80_000), mesh)  # new wps bucket
    assert len(c._seq_scorers) == 2


def test_committee_predict_song_sequence_needs_cnn(rng):
    from consensus_entropy_tpu.models.committee import Committee

    c = Committee([], [], TINY)
    with pytest.raises(ValueError, match="no CNN members"):
        c.predict_song_sequence(_song(rng, 10_000), make_seq_mesh())
