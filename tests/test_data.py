"""Data layer on synthetic fixtures (.mat annotations, openSMILE-style CSVs)."""

import numpy as np
import pandas as pd
import pytest
from scipy.io import savemat
from scipy.stats import entropy as scipy_entropy

from consensus_entropy_tpu.data import amg, deam

N_SONGS, N_USERS = 12, 9


@pytest.fixture
def amg_fixture(tmp_path, rng):
    # song_label (songs, users, 2=[valence, arousal]) with NaN holes
    lab = rng.uniform(-1, 1, size=(N_SONGS, N_USERS, 2))
    holes = rng.uniform(size=(N_SONGS, N_USERS)) < 0.35
    lab[holes] = np.nan
    # every song keeps at least one annotation
    lab[:, 0, :] = np.where(np.isnan(lab[:, 0, :]), 0.5, lab[:, 0, :])
    song_ids = np.arange(101, 101 + N_SONGS)
    mat = str(tmp_path / "AMG1608.mat")
    mapping = str(tmp_path / "1608_song_id.mat")
    savemat(mat, {"song_label": lab})
    savemat(mapping, {"mat_id2song_id": song_ids.reshape(-1, 1)})
    return mat, mapping, lab, song_ids


def test_load_annotations(amg_fixture):
    mat, mapping, lab, song_ids = amg_fixture
    df = amg.load_annotations(mat, mapping)
    n_valid = np.sum(~np.isnan(lab[:, :, 0]))
    assert len(df) == n_valid
    assert set(df.song_id.unique()) == set(song_ids)
    # spot-check one annotation end to end, incl. [valence, arousal] order
    s, u = song_ids[3], 0
    row = df[(df.song_id == s) & (df.user_id == u)].iloc[0]
    np.testing.assert_allclose(row.valence, lab[3, 0, 0])
    np.testing.assert_allclose(row.arousal, lab[3, 0, 1])
    a, v = lab[3, 0, 1], lab[3, 0, 0]
    if a >= 0 and v >= 0:
        assert row.quadrant == 0
    assert set(df.quadrant.unique()) <= {0, 1, 2, 3}


def test_hc_table_rounded_frequencies(amg_fixture):
    mat, mapping, lab, song_ids = amg_fixture
    df = amg.load_annotations(mat, mapping)
    hc = amg.hc_frequency_table(df)
    assert list(hc.columns) == ["Q1", "Q2", "Q3", "Q4"]
    assert len(hc) == N_SONGS
    # rows are frequencies rounded to 3 decimals (amg_test.py:115)
    sid = song_ids[0]
    mine = df[df.song_id == sid]
    want = np.round(np.bincount(mine.quadrant, minlength=4) / len(mine), 3)
    np.testing.assert_allclose(hc.loc[sid].values, want)
    # entropy over rows is finite (consumed by the hc scorer)
    assert np.isfinite(scipy_entropy(hc.values, axis=1)).all()


def test_filter_users(amg_fixture):
    mat, mapping, lab, _ = amg_fixture
    df = amg.load_annotations(mat, mapping)
    counts = df.groupby("user_id").size()
    thresh = int(counts.median())
    out, users = amg.filter_users(df, thresh)
    assert set(users) == set(counts[counts >= thresh].index)
    assert out.user_id.isin(users).all()


@pytest.fixture
def feats_fixture(tmp_path, rng):
    cols = (["F0final_sma_stddev"]
            + [f"feat_{i}" for i in range(3)]
            + ["mfcc_sma_de[14]_amean"])
    fdir = tmp_path / "feats"
    fdir.mkdir()
    for sid in range(101, 101 + N_SONGS):
        k = int(rng.integers(3, 7))
        df = pd.DataFrame(rng.standard_normal((k, len(cols))), columns=cols)
        df.insert(0, "frameTime", np.arange(k) * 1.0)
        df.insert(0, "junk_before", 0.0)  # column outside the slice
        df.to_csv(fdir / f"{sid}.csv", sep=";", index=False)
    return str(fdir), cols


def test_load_feature_pool_assemble_and_cache(feats_fixture, tmp_path):
    fdir, cols = feats_fixture
    cache = str(tmp_path / "dataset_feats.csv")
    pool = amg.load_feature_pool(cache, fdir)
    assert pool.X.shape[1] == len(cols)  # slice excludes junk + frameTime
    assert pool.n_songs == N_SONGS
    assert all(isinstance(s, (int, np.integer)) for s in pool.song_ids)
    # full-pool scaling (amg_test.py:64)
    np.testing.assert_allclose(pool.X.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(pool.X.std(axis=0), 1.0, atol=1e-3)
    # second load hits the cache and matches
    pool2 = amg.load_feature_pool(cache, None)
    np.testing.assert_allclose(pool2.X, pool.X, rtol=1e-5)


def test_user_pool(amg_fixture, feats_fixture, tmp_path):
    mat, mapping, *_ = amg_fixture
    fdir, _ = feats_fixture
    df = amg.load_annotations(mat, mapping)
    pool = amg.load_feature_pool(None, fdir)
    sub, labels = amg.user_pool(pool, df, 0)
    my_songs = set(df[df.user_id == 0].song_id)
    assert set(labels) == my_songs & set(pool.song_ids)
    assert sub.n_songs == len(labels)


# ---------------------------------------------------------------- DEAM ----


@pytest.fixture
def deam_fixture(tmp_path, rng):
    cols = (["F0final_sma_stddev"] + [f"f{i}" for i in range(2)]
            + ["mfcc_sma_de[14]_amean"])
    fdir = tmp_path / "features"
    fdir.mkdir()
    times = np.arange(15.0, 20.0, 0.5)  # DEAM: 500 ms steps from 15 s
    a_rows, v_rows = [], []
    for sid in (3, 4, 5):
        df = pd.DataFrame(rng.standard_normal((len(times), len(cols))),
                          columns=cols)
        df.insert(0, "frameTime", times)
        df.to_csv(fdir / f"{sid}.csv", sep=";", index=False)
        cols_ms = [f"sample_{int(t * 1000)}ms" for t in times]
        a = {"song_id": sid}
        v = {"song_id": sid}
        for c in cols_ms:
            a[c] = rng.uniform(-1, 1)
            v[c] = rng.uniform(-1, 1)
        a_rows.append(a)
        v_rows.append(v)
    # song 5: arousal annotations one step shorter → join keeps the shorter
    del a_rows[2][f"sample_{int(times[-1] * 1000)}ms"]
    a_csv, v_csv = str(tmp_path / "arousal.csv"), str(tmp_path / "valence.csv")
    pd.DataFrame(a_rows).to_csv(a_csv, index=False)
    pd.DataFrame(v_rows).to_csv(v_csv, index=False)
    return str(fdir), a_csv, v_csv


def test_deam_join(deam_fixture, tmp_path):
    fdir, a_csv, v_csv = deam_fixture
    df = deam.load_dataset(fdir, a_csv, v_csv,
                           cache_csv=str(tmp_path / "cache.csv"))
    assert set(df.song_id.unique()) == {3, 4, 5}
    # song 5 dropped its last frame (shorter arousal row wins)
    assert (df[df.song_id == 5].shape[0]
            == df[df.song_id == 3].shape[0] - 1)
    assert set(df.quadrants.unique()) <= {"Q1", "Q2", "Q3", "Q4"}
    # quadrant matches the DEAM-variant geometry row-wise
    from consensus_entropy_tpu.labels import quadrant_deam_np

    want = quadrant_deam_np(df.arousal.values, df.valence.values)
    got = np.array([int(q[1]) - 1 for q in df.quadrants])
    np.testing.assert_array_equal(got, want)
    # cache round-trip
    df2 = deam.load_dataset(fdir, a_csv, v_csv,
                            cache_csv=str(tmp_path / "cache.csv"))
    assert len(df2) == len(df)


def test_deam_training_arrays(deam_fixture):
    fdir, a_csv, v_csv = deam_fixture
    df = deam.load_dataset(fdir, a_csv, v_csv)
    X, y, sids = deam.training_arrays(df)
    assert X.shape[0] == len(df) == len(y) == len(sids)
    assert X.shape[1] == 4  # the feature slice
    np.testing.assert_allclose(X.mean(axis=0), 0.0, atol=1e-4)


def test_load_feature_pool_real_amg_shape_fftmag(tmp_path):
    """The real AMG1608 cache: 1608 songs and the newer openSMILE column
    vintage (mfcc block prefixed ``pcm_fftMag_``).  The loader must dispatch
    on whichever stop column is present, exactly as the DEAM side does
    (``amg_test.py:57-64`` reads the same table)."""
    from tests.synth_data import FEATURE_COLS_FFTMAG, amg_dataset_frame

    rng = np.random.default_rng(5)
    df = amg_dataset_frame(rng, n_songs=1608,
                           feature_cols=FEATURE_COLS_FFTMAG)
    csv = tmp_path / "dataset_feats.csv"
    df.to_csv(csv, sep=";", index=False)
    pool = amg.load_feature_pool(str(csv))
    assert pool.n_songs == 1608
    assert pool.X.shape == (len(df), len(FEATURE_COLS_FFTMAG))
    # full-pool scaling applied (amg_test.py:64)
    np.testing.assert_allclose(pool.X.mean(axis=0), 0.0, atol=1e-4)
    # unknown column layouts fail loud, not silently empty
    bad = df.rename(columns={"pcm_fftMag_mfcc_sma_de[14]_amean": "oops"})
    bad.to_csv(tmp_path / "bad.csv", sep=";", index=False)
    with pytest.raises(ValueError, match="unrecognized feature columns"):
        amg.load_feature_pool(str(tmp_path / "bad.csv"))
