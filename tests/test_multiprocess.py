"""REAL two-process ``jax.distributed`` integration test (2 procs x 4
virtual CPU devices = 8 global): exercises the multi-host code paths that
single-process virtual-mesh tests cannot — process-local feeds onto a mesh
with non-addressable devices, the gather-back of pool-sharded outputs, the
rand-key replicated feed, and lockstep selection across processes."""

import json
import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest


def _jax_supports_multiprocess_cpu() -> bool:
    """The worker needs ``jax_num_cpu_devices`` AND a CPU backend that can
    run cross-process collectives; both landed together in newer jax.  On
    this image's 0.4.37 the option is absent and any collective raises
    "Multiprocess computations aren't implemented on the CPU backend", so
    the real-two-process tests cannot run here — the single-process
    8-device virtual-mesh suite still covers the sharded code paths."""
    try:
        jax.config.jax_num_cpu_devices
        return True
    except AttributeError:
        return False


pytestmark = pytest.mark.skipif(
    not _jax_supports_multiprocess_cpu(),
    reason="this jax build cannot run multiprocess collectives on CPU")

WORKER = r"""
import json, sys
pid, port = int(sys.argv[1]), sys.argv[2]
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=pid)
import numpy as np
from consensus_entropy_tpu.al.acquisition import Acquirer
from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.data.audio import DeviceWaveformStore
from consensus_entropy_tpu.models import short_cnn
from consensus_entropy_tpu.models.committee import CNNMember, Committee
from consensus_entropy_tpu.parallel import multihost

assert jax.process_count() == 2 and len(jax.devices()) == 8
mesh = multihost.global_pool_mesh()

# -- Acquirer through the sharded scorers with per-host feeds -------------
rng = np.random.default_rng(7)  # same stream on both processes
songs = [f"s{i:02d}" for i in range(20)]
hc = np.round(rng.dirichlet(np.ones(4), 20), 3).astype(np.float32)
results = {}
for mode in ("mc", "mix", "hc", "rand"):
    acq = Acquirer(songs, hc, queries=4, mode=mode, seed=3, mesh=mesh)
    probs = rng.dirichlet(np.ones(4), (3, 20)).astype(np.float32)
    picked = acq.select(probs[:, [songs.index(s)
                                  for s in acq.remaining_songs]])
    results[mode] = list(map(str, picked))

# -- Committee CNN forward: feed_repl/feed_rows/gather_rows ---------------
cfg = CNNConfig(n_channels=2, n_mels=16, n_fft=64, hop_length=32,
                n_layers=2, input_length=512)
members = [CNNMember(f"it_{i}",
                     short_cnn.init_variables(jax.random.key(i), cfg), cfg)
           for i in range(2)]
committee = Committee([], members, cfg, mesh=mesh)
waves = {s: (np.sin(np.arange(700) * (0.01 + 0.001 * i))
             .astype(np.float32)) for i, s in enumerate(songs)}
store = DeviceWaveformStore(waves, cfg.input_length)
cnn_probs = np.asarray(committee.pool_probs(None, store, songs,
                                            jax.random.key(5)))
results["cnn_checksum"] = float(np.sum(cnn_probs))
results["cnn_shape"] = list(cnn_probs.shape)

# -- member-sharded retraining across processes ---------------------------
# 3 members padded to 8 member slots spanning BOTH processes: per-process
# member feeds, lockstep SPMD epochs, replicated best checkpoints back.
from consensus_entropy_tpu.config import TrainConfig
from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer
from consensus_entropy_tpu.parallel.mesh import make_training_mesh

train_mesh = make_training_mesh(dp=1, member=8)
trainer = CNNTrainer(cfg, TrainConfig(batch_size=2))
tr_y = np.eye(4, dtype=np.float32)[[i % 4 for i in range(6)]]
te_y = np.eye(4, dtype=np.float32)[[i % 4 for i in range(2)]]
m3 = [short_cnn.init_variables(jax.random.key(10 + i), cfg)
      for i in range(3)]
best3, hist3 = trainer.fit_many(m3, store, songs[:6], tr_y, songs[6:8],
                                te_y, jax.random.key(9), n_epochs=2,
                                mesh=train_mesh)
results["retrain_losses"] = [round(h["val_loss"], 6) for h in hist3[0]]
results["retrain_checksum"] = float(sum(
    float(np.sum(np.asarray(l)))
    for l in jax.tree.leaves(best3[0]["params"])))

# -- coordination primitives ----------------------------------------------
results["is_coord"] = multihost.is_coordinator()
flag = multihost.broadcast_flag(pid == 0)
results["flag"] = bool(flag)
multihost.sync("done")
print("RESULT " + json.dumps(results), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo}
    env.pop("JAX_PLATFORMS", None)
    return env


def _run_pair(argv_per_pid, env, timeout=300) -> list:
    """Spawn both workers, reap BOTH on any failure (one worker dying
    leaves the other blocked in a distributed barrier), return stdouts."""
    procs = [subprocess.Popen(argv, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for argv in argv_per_pid]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs


def test_two_process_distributed_scoring(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    port = str(_free_port())
    outs = _run_pair([[sys.executable, str(worker), str(pid), port]
                      for pid in range(2)], _worker_env())

    parsed = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][0]
        parsed.append(json.loads(line[7:]))

    r0, r1 = parsed
    # lockstep: both processes select identical query batches in all modes
    for mode in ("mc", "mix", "hc", "rand"):
        assert r0[mode] == r1[mode], mode
    for mode in ("mc", "hc", "rand"):
        assert len(r0[mode]) == 4
    # mix dedups a song surfacing from both stacked blocks (amg_test.py:491
    # semantics), so its batch may be smaller than q
    assert 1 <= len(r0["mix"]) <= 4
    # gather-back: both hold the identical host-complete CNN table
    assert r0["cnn_shape"] == r1["cnn_shape"] == [2, 20, 4]
    assert abs(r0["cnn_checksum"] - r1["cnn_checksum"]) < 1e-5
    # member-sharded retrain: finite lockstep losses, identical replicated
    # best params on both processes
    assert r0["retrain_losses"] == r1["retrain_losses"]
    assert all(np.isfinite(v) for v in r0["retrain_losses"])
    assert len(r0["retrain_losses"]) == 2
    assert abs(r0["retrain_checksum"] - r1["retrain_checksum"]) < 1e-4
    assert np.isfinite(r0["retrain_checksum"])
    # coordinator roles + broadcast agreement
    assert r0["is_coord"] is True and r1["is_coord"] is False
    assert r0["flag"] is True and r1["flag"] is True


def test_two_process_al_cli_end_to_end(tmp_path):
    """The FULL AL CLI in two real jax.distributed processes sharing one
    workspace: coordinator owns every file, skip decisions broadcast, both
    processes finish rc 0 with identical results."""
    from tests.synth_data import build_synth_roots

    roots = build_synth_roots(tmp_path, np.random.default_rng(11))
    env = _worker_env()

    # pre-train (single process; just populates the shared models dir)
    pre = subprocess.run(
        [sys.executable, "-m", "consensus_entropy_tpu.cli.deam_classifier",
         "-cv", "2", "-m", "gnb", "--device", "cpu",
         "--models-root", roots["models"], "--deam-root", roots["deam"],
         "--amg-root", roots["amg"]],
        capture_output=True, text=True, env=env, timeout=300)
    assert pre.returncode == 0, pre.stdout + pre.stderr

    port = str(_free_port())
    args = [sys.executable, "-m", "consensus_entropy_tpu.cli.amg_test",
            "-q", "4", "-e", "2", "-m", "mc", "-n", "10",
            "--max-users", "2", "--mesh", "auto", "--device", "cpu",
            "--models-root", roots["models"], "--deam-root", roots["deam"],
            "--amg-root", roots["amg"]]
    outs = _run_pair(
        [args + ["--distributed", f"localhost:{port},2,{pid}"]
         for pid in range(2)], env)

    # both processes computed in lockstep and report the same final F1
    finals = [[l for l in out.splitlines() if "final committee F1" in l]
              for out in outs]
    assert finals[0] and finals[0] == finals[1]
    # the coordinator wrote each user's reports/state exactly once; DONE set
    users_dir = os.path.join(roots["models"], "users")
    users = sorted(os.listdir(users_dir))
    assert len(users) == 2
    for u in users:
        udir = os.path.join(users_dir, u, "mc")
        assert os.path.exists(os.path.join(udir, "DONE"))
        metrics = [json.loads(l)
                   for l in open(os.path.join(udir, "metrics.jsonl"))]
        assert len(metrics) == 3  # epoch0 + 2 AL iterations, no duplicates
