"""The MusiCNN-style multi-shape family (config.arch='musicnn'):
vertical-timbral + horizontal-temporal front-end over log-mel, temporal
mid-end, shared head.  Reference block semantics: the vendored (unused)
``Conv_V``/``Conv_H`` at ``/root/reference/short_cnn.py:128-160``."""

import jax
import numpy as np
import pytest

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.models import short_cnn

TINY_M = CNNConfig(n_channels=4, n_mels=16, n_fft=64, hop_length=32,
                   n_layers=3, input_length=2048, arch="musicnn")


@pytest.fixture(scope="module")
def m_vars():
    return short_cnn.init_variables(jax.random.key(0), TINY_M)


def test_musicnn_geometry_validation():
    with pytest.raises(ValueError, match="collapses"):
        CNNConfig(n_channels=2, n_mels=16, n_fft=64, hop_length=32,
                  n_layers=8, input_length=2048, arch="musicnn")
    CNNConfig(arch="musicnn")  # default geometry is valid


def test_musicnn_forward_and_branches(m_vars, rng):
    x = rng.standard_normal((3, TINY_M.input_length)).astype(np.float32)
    out = np.asarray(short_cnn.apply_infer(m_vars, x, TINY_M))
    assert out.shape == (3, 4)
    assert np.isfinite(out).all()
    fe = m_vars["params"]["MusicnnFrontEnd_0"]
    # two vertical (timbral) + two horizontal (temporal) branches
    assert {"v0_conv", "v1_conv", "h0_conv", "h1_conv"} <= set(fe)
    # vertical kernels span a fraction of the mel axis (Conv_V)
    assert fe["v0_conv"]["kernel"].shape[0] == int(16 * 0.4)
    assert fe["v1_conv"]["kernel"].shape[0] == int(16 * 0.7)
    # horizontal kernels are long 1-D time filters (Conv_H)
    assert fe["h0_conv"]["kernel"].shape[0] == 32
    assert fe["h1_conv"]["kernel"].shape[0] == 64
    mids = [k for k in m_vars["params"] if k.startswith("mid")]
    assert len(mids) == 2 * TINY_M.n_layers  # conv + bn per stage


def test_musicnn_train_and_committee(m_vars, rng):
    x = rng.standard_normal((4, TINY_M.input_length)).astype(np.float32)
    out, new_stats = short_cnn.apply_train(
        m_vars, x, jax.random.key(1), TINY_M)
    assert out.shape == (4, 4)
    assert any(not np.allclose(a, b) for a, b in zip(
        jax.tree.leaves(m_vars["batch_stats"]),
        jax.tree.leaves(new_stats)))
    members = [short_cnn.init_variables(jax.random.key(i), TINY_M)
               for i in range(2)]
    probs = np.asarray(short_cnn.committee_infer(
        short_cnn.stack_params(members), x, TINY_M))
    assert probs.shape == (2, 4, 4)


def test_musicnn_trainer_and_registry(rng, tmp_path):
    from consensus_entropy_tpu.config import TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer
    from consensus_entropy_tpu.models.committee import CNNMember
    from consensus_entropy_tpu.train.pretrain import MODEL_CHOICES

    assert "cnn_musicnn_jax" in MODEL_CHOICES
    waves = {f"s{i}": (rng.standard_normal(2500) * 0.05).astype(np.float32)
             for i in range(8)}
    store = DeviceWaveformStore(waves, TINY_M.input_length)
    ids = list(waves)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
    trainer = CNNTrainer(TINY_M, TrainConfig(batch_size=4))
    v0 = short_cnn.init_variables(jax.random.key(0), TINY_M)
    best, hist = trainer.fit(v0, store, ids[:6], y[:6], ids[6:], y[6:],
                             jax.random.key(1), n_epochs=2)
    assert np.isfinite([h["val_loss"] for h in hist]).all()
    m = CNNMember("it_0", best, TINY_M)
    path = str(tmp_path / "classifier_cnn_musicnn.it_0.msgpack")
    m.save(path)
    assert CNNMember.load(path).config.arch == "musicnn"
