"""The sample-level squeeze-excitation 1-D family (config.arch='se1d'):
geometry, SE gating, forward/training, committee vmap, registry.  Reference
block semantics: the vendored (unused) ``ResSE_1d`` at
``/root/reference/short_cnn.py:85-125``; the trunk consumes the RAW
waveform — no spectrogram frontend."""

import dataclasses

import jax
import numpy as np
import pytest

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.models import short_cnn

# 2187 = 3^7: stem (/3) + 3 blocks (/3 each) leave 27 samples of time
TINY_SE = CNNConfig(n_channels=4, n_layers=3, input_length=2187,
                    arch="se1d")


@pytest.fixture(scope="module")
def se_vars():
    return short_cnn.init_variables(jax.random.key(0), TINY_SE)


def test_se1d_geometry_validation():
    CNNConfig(n_channels=2, n_layers=3, input_length=81, arch="se1d")
    with pytest.raises(ValueError, match="collapses"):
        CNNConfig(n_channels=2, n_layers=4, input_length=81, arch="se1d")
    # the reference crop is 3^10 — exactly the default 7-block geometry
    CNNConfig(arch="se1d")


def test_se1d_forward_and_params(se_vars, rng):
    x = rng.standard_normal((3, TINY_SE.input_length)).astype(np.float32)
    out = np.asarray(short_cnn.apply_infer(se_vars, x, TINY_SE))
    assert out.shape == (3, 4)
    assert np.isfinite(out).all()
    assert (out >= 0).all() and (out <= 1).all()
    p = se_vars["params"]
    assert "stem" in p and "dense1" in p  # raw-waveform stem + shared head
    blocks = [k for k in p if k.startswith("SEBlock1d")]
    assert len(blocks) == TINY_SE.n_layers
    assert "se_dense1" in p[blocks[0]]  # the excitation gate
    # first block changes width (4 != stem's 4?) — widths equal at block 0,
    # so no projection there; the first widening block must have one
    widths = TINY_SE.channel_widths
    first_widen = next(i for i, w in enumerate(widths) if
                       w != (widths[i - 1] if i else widths[0]))
    assert "conv_proj" in p[f"SEBlock1d_{first_widen}"]


def test_se1d_train_step_and_committee(se_vars, rng):
    x = rng.standard_normal((4, TINY_SE.input_length)).astype(np.float32)
    out, new_stats = short_cnn.apply_train(
        se_vars, x, jax.random.key(1), TINY_SE)
    assert out.shape == (4, 4)
    assert any(not np.allclose(a, b) for a, b in zip(
        jax.tree.leaves(se_vars["batch_stats"]),
        jax.tree.leaves(new_stats)))
    members = [short_cnn.init_variables(jax.random.key(i), TINY_SE)
               for i in range(3)]
    probs = np.asarray(short_cnn.committee_infer(
        short_cnn.stack_params(members), x, TINY_SE))
    assert probs.shape == (3, 4, 4)


def test_se1d_trainer_fit(rng):
    from consensus_entropy_tpu.config import TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer

    waves = {f"s{i}": (rng.standard_normal(2500) * 0.05).astype(np.float32)
             for i in range(8)}
    store = DeviceWaveformStore(waves, TINY_SE.input_length)
    ids = list(waves)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
    trainer = CNNTrainer(TINY_SE, TrainConfig(batch_size=4))
    v0 = short_cnn.init_variables(jax.random.key(0), TINY_SE)
    best, hist = trainer.fit(v0, store, ids[:6], y[:6], ids[6:], y[6:],
                             jax.random.key(1), n_epochs=2)
    assert len(hist) == 2
    assert np.isfinite([h["val_loss"] for h in hist]).all()


def test_se1d_checkpoint_and_registry(se_vars, tmp_path):
    from consensus_entropy_tpu.models.committee import CNNMember, Committee
    from consensus_entropy_tpu.train.pretrain import MODEL_CHOICES

    assert "cnn_se1d_jax" in MODEL_CHOICES
    m = CNNMember("it_0", se_vars, TINY_SE)
    path = str(tmp_path / "classifier_cnn_se1d.it_0.msgpack")
    m.save(path)
    vgg_cfg = dataclasses.replace(TINY_SE, arch="vgg", n_mels=32,
                                  n_layers=3, input_length=8192)
    m2 = CNNMember.load(path, vgg_cfg)
    assert m2.config.arch == "se1d"
    c = Committee([], [m2], vgg_cfg)
    assert c.config.arch == "se1d"


def test_al_cli_cnn_arch_flag():
    """--cnn-arch reaches config construction: a non-vgg geometry that vgg
    validation would reject must parse when the arch is given."""
    from consensus_entropy_tpu.cli.common import resolve_cnn_config

    json_cfg = '{"n_channels": 4, "n_layers": 2, "input_length": 729}'
    with pytest.raises(ValueError, match="collapses"):
        resolve_cnn_config(json_cfg)  # vgg rules reject 729 samples
    cfg = resolve_cnn_config(json_cfg, arch="se1d")
    assert cfg.arch == "se1d" and cfg.input_length == 729


def test_arch_conflict_rejected():
    from consensus_entropy_tpu.cli.common import resolve_cnn_config

    with pytest.raises(ValueError, match="drop one"):
        resolve_cnn_config('{"arch": "se1d"}', arch="vgg")
    # agreeing values are fine
    assert resolve_cnn_config('{"arch": "res"}', arch="res").arch == "res"
