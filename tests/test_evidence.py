"""Evidence harness: sweep mechanics, paired-test math, analyze round-trip.

The statistical CLAIM (mc>rand at p<0.05) is established by the committed
24-seed artifact (EVIDENCE_r03.json) — these tests pin the machinery, not
the p-values, at budgets small enough for CI.
"""

import json
import os

import numpy as np
import pytest

from consensus_entropy_tpu.al import evidence


def test_make_user_is_seed_deterministic():
    a = evidence.make_user(3, n_songs=40)
    b = evidence.make_user(3, n_songs=40)
    assert a.labels == b.labels
    np.testing.assert_array_equal(a.pool.X, b.pool.X)
    np.testing.assert_array_equal(a.hc_rows, b.hc_rows)
    # hc rows are aligned with pool.song_ids and rounded to 3 decimals
    # (amg_test.py:109-117 parity)
    assert a.hc_rows.shape == (40, 4)
    np.testing.assert_array_equal(a.hc_rows, np.round(a.hc_rows, 3))


def test_run_one_modes_and_member_counts(tmp_path):
    per_epoch = evidence.run_one(0, "mc", str(tmp_path), queries=3,
                                 epochs=2, n_songs=60)
    assert len(per_epoch) == 3  # epoch0 baseline + 2 iterations
    assert all(len(e) == 5 for e in per_epoch)  # 5 GNB fold-members
    # re-running the same cell must not accumulate stale records
    per_epoch2 = evidence.run_one(0, "mc", str(tmp_path), queries=3,
                                  epochs=2, n_songs=60)
    assert len(per_epoch2) == 3


def test_run_one_with_cnn_members(tmp_path):
    per_epoch = evidence.run_one(0, "mc", str(tmp_path), queries=3,
                                 epochs=2, n_songs=50, cnn_members=1)
    assert len(per_epoch) == 3
    assert all(len(e) == 6 for e in per_epoch)  # 5 GNB + 1 CNN
    assert all(np.isfinite(e).all() for e in per_epoch)


@pytest.mark.filterwarnings(
    "ignore:Precision loss occurred:RuntimeWarning")
def test_paired_tests_shapes_and_direction():
    # synthetic results where "good" dominates "rand" by construction;
    # the paired diffs are EXACTLY constant, so scipy's t-test warns about
    # catastrophic cancellation in the variance — expected for this input
    rng = np.random.default_rng(0)
    results = {"good": {}, "rand": {}}
    for seed in range(10):
        base = rng.uniform(0.5, 0.7, 3)
        results["rand"][seed] = [list(base), list(base + 0.01)]
        results["good"][seed] = [list(base), list(base + 0.06)]
    tests = evidence.paired_tests(results, baseline="rand")
    t = tests["good>rand"]
    assert t["per_member_final"]["p"] < 0.01
    assert t["per_member_final"]["df"] == 29  # 10 seeds x 3 members - 1
    assert t["per_seed_final"]["df"] == 9
    assert t["per_member_final"]["mean_diff"] == pytest.approx(0.05)


@pytest.mark.filterwarnings(
    "ignore:Precision loss occurred:RuntimeWarning")
def test_analyze_users_round_trip(tmp_path):
    # write the CLI's layout by hand; analyze must pair users and test
    # (constant paired diffs -> expected scipy precision warning, as above)
    for uid in ("u0", "u1", "u2"):
        for mode, lift in (("mc", 0.05), ("rand", 0.0)):
            d = tmp_path / uid / mode
            d.mkdir(parents=True)
            f1 = [0.5 + lift + 0.01 * int(uid[1]), 0.6 + lift]
            with open(d / "metrics.jsonl", "w") as fh:
                fh.write(json.dumps({"epoch": -1, "f1": [0.5, 0.6]}) + "\n")
                fh.write(json.dumps({"epoch": 0, "f1": f1}) + "\n")
    out = evidence.analyze_users(str(tmp_path), modes=("mc", "rand"))
    assert out["n_users"] == {"mc": 3, "rand": 3}
    t = out["tests"]["mc>rand"]
    assert t["n_users_paired"] == 3
    assert t["per_member_final"]["mean_diff"] == pytest.approx(0.05)
    assert t["per_member_final"]["p"] < 0.05


def test_analyze_users_unpaired_committee_sizes(tmp_path):
    for uid, mode, f1 in (("u0", "mc", [0.5, 0.6, 0.7]),
                          ("u0", "rand", [0.5, 0.6])):
        d = tmp_path / uid / mode
        d.mkdir(parents=True)
        with open(d / "metrics.jsonl", "w") as fh:
            fh.write(json.dumps({"epoch": 0, "f1": f1}) + "\n")
    out = evidence.analyze_users(str(tmp_path), modes=("mc", "rand"))
    assert "skipped" in out["tests"]["mc>rand"]


def test_committed_evidence_artifact_claims_hold():
    """The committed EVIDENCE_r03.json must actually contain the claims the
    README states: mc>rand and mix>rand significant at p<0.05 on the
    per-member pairing."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "EVIDENCE_r03.json")
    with open(path) as fh:
        report = json.load(fh)
    for name in ("mc>rand", "mix>rand", "hc>rand"):
        assert report["tests"][name]["per_member_final"]["p"] < 0.05, name
    assert report["tests"]["mc>rand"]["per_member_final"]["p"] < 1e-4


def test_make_committee_from_registry(tmp_path):
    """Registry-loaded CNN fold-members (the reference's copy-the-DEAM-
    committee-per-user structure) + SGD fold-members: members load clean,
    carry sweep names, and score through the committee."""
    import jax

    from consensus_entropy_tpu.models import short_cnn
    from consensus_entropy_tpu.utils.checkpoint import save_variables

    for i in range(3):
        v = short_cnn.init_variables(jax.random.key(i), evidence.CNN_CFG)
        save_variables(str(tmp_path / f"classifier_cnn.it_{i}.msgpack"), v,
                       meta={"kind": "cnn_jax", "name": f"it_{i}"})
    # enough songs that every class appears (SGD fit requires the full
    # class universe; CLASS_P's rare classes can vanish from tiny pools)
    data = evidence.make_user(0, n_songs=40, waves=True)
    com = evidence.make_committee(0, data, cnn_members=3, sgd_members=2,
                                  cnn_registry=str(tmp_path))
    assert [m.name for m in com.cnn_members] == ["cnn0", "cnn1", "cnn2"]
    assert not any(m.ckpt_dirty for m in com.cnn_members)
    assert sum(m.name.startswith("sgd") for m in com.host_members) == 2
    assert sum(m.name.startswith("gnb") for m in com.host_members) == 5
    probs = np.asarray(com.pool_probs(data.pool, data.store,
                                      data.pool.song_ids[:4],
                                      jax.random.key(1)))
    assert probs.shape == (10, 4, 4)  # (3 cnn + 7 host, songs, classes)
    assert np.isfinite(probs).all()


def test_sweep_with_registry_runs_production_loop(tmp_path):
    """A 1-seed mc/rand sweep with a registry committee exercises the full
    production path (scoring, 100-epoch default would be slow — pass
    cnn_members to control retrain depth)."""
    import jax

    from consensus_entropy_tpu.models import short_cnn
    from consensus_entropy_tpu.utils.checkpoint import save_variables

    reg = tmp_path / "reg"
    reg.mkdir()
    for i in range(2):
        v = short_cnn.init_variables(jax.random.key(i), evidence.CNN_CFG)
        save_variables(str(reg / f"classifier_cnn.it_{i}.msgpack"), v,
                       meta={"kind": "cnn_jax", "name": f"it_{i}"})
    per_epoch = evidence.run_one(
        0, "mc", str(tmp_path / "wk"), queries=3, epochs=2, n_songs=40,
        cnn_members=2, cnn_retrain_epochs=2, cnn_registry=str(reg))
    # epoch0 baseline + 2 AL iterations; 5 gnb + 2 cnn members
    assert len(per_epoch) == 3
    assert all(len(e) == 7 for e in per_epoch)


@pytest.mark.filterwarnings(
    "ignore:Precision loss occurred:RuntimeWarning")
def test_species_tests_slices_members():
    """species_tests restricts the per-member pairing to one committee
    slice; a species that improves under mc and one that doesn't must
    separate (constant paired diffs -> expected scipy precision warning,
    as in the other fixed-fixture tests above)."""
    results = {
        "mc": {s: [[0.9, 0.9, 0.5, 0.5]] for s in range(6)},
        "rand": {s: [[0.6, 0.6, 0.5, 0.5]] for s in range(6)},
    }
    # add per-seed jitter so the paired t-test is defined (non-zero var)
    for s in range(6):
        results["mc"][s] = [[v + 0.001 * s for v in results["mc"][s][0]]]
        results["rand"][s] = [[v + 0.001 * s
                               for v in results["rand"][s][0]]]
    out = evidence.species_tests(
        results, {"cnn": slice(0, 2), "host": slice(2, 4)})
    assert out["cnn:mc>rand"]["p"] < 0.01
    assert out["cnn:mc>rand"]["mean_diff"] == pytest.approx(0.3)
    assert out["host:mc>rand"]["mean_diff"] == pytest.approx(0.0)
