"""Crash-safe serving: journal restart recovery, watchdog, backoff
re-admission + poison list, dispatch circuit breaker.

The headline drill kills the server at serve-layer boundaries
(``serve.admit`` / ``serve.journal.append`` / ``serve.dispatch`` /
``serve.collect``), restarts it from ``serve_journal.jsonl`` and asserts
that EVERY submitted user finishes with results bit-identical to an
uninterrupted run — recovery is exercised, not trusted.  Tier-1 keeps the
pure-host units and the flaky-mix smoke (the restart mechanism stays
tier-1 via the FUSED-arm cross-arm case in ``tests/test_fused_step.py``);
the mc 3-user restart case (demoted in PR 9's tier-1 budget trade), the
kill matrix, the 4-mode restart matrix and the watchdog/backoff/poison/
breaker drills are ``slow`` and run via ``scripts/fault_matrix.sh``.

Parity is exact (``==`` on float lists) throughout: recovery replays the
same sessions from the same durable workspaces, and degraded (per-user)
dispatch is the literal sequential scoring path.
"""

import dataclasses

import pytest

from consensus_entropy_tpu.al import workspace
from consensus_entropy_tpu.al.loop import ALLoop
from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, FleetUser
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.resilience.retry import backoff_delay
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    DispatchBreaker,
    FleetServer,
    PoisonList,
    ServeConfig,
    Watchdog,
    WatchdogTimeout,
)
from tests.test_fleet import _cfg, _committee, _user_data

pytestmark = [pytest.mark.serve, pytest.mark.faults]


# -- pure-host units (no jax) ---------------------------------------------


def test_journal_replay_and_recovery_order(tmp_path):
    """The WAL replays into per-user dispositions; a half-written tail
    line (the crash artifact an fsynced append can leave) is ignored."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        for ev, u in [("enqueue", "a"), ("enqueue", "b"), ("admit", "a"),
                      ("enqueue", "c"), ("admit", "b"), ("finish", "a"),
                      ("fail", "b")]:
            j.append(ev, u)
    with open(jp, "ab") as f:
        f.write(b'{"event": "fin')  # torn tail write
    st = AdmissionJournal(jp).state
    assert st.finished == {"a"}
    assert st.in_flight == ["b"]  # last event fail: still re-admittable
    assert st.queued == ["c"]
    assert st.admits == {"a": 1, "b": 1} and st.fails == {"b": 1}
    # in-flight first, queued next, unseen, then finished last (cheap
    # skips that let the driver print its usual message)
    assert st.recovery_order(["a", "b", "c", "d"]) == ["b", "c", "d", "a"]
    with pytest.raises(ValueError, match="unknown journal event"):
        AdmissionJournal(None).append("bogus", "u")


def test_journal_append_is_a_fault_point(tmp_path):
    """``serve.journal.append`` fires BEFORE the write: a kill there dies
    with the transition un-journaled, which replay treats as 'never
    happened'."""
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp)
    j.append("enqueue", "a")
    with faults.inject(FaultRule("serve.journal.append", "kill")) as inj:
        with pytest.raises(InjectedKill):
            j.append("admit", "a")
        assert inj.fired
    j.close()
    st = AdmissionJournal(jp).state
    assert st.queued == ["a"] and not st.in_flight  # admit never landed


def test_poison_list_persists_and_skips(tmp_path):
    pp = str(tmp_path / "p.jsonl")
    p = PoisonList(pp)
    assert "x" not in p
    p.add("x", error="boom", attempts=3)
    assert "x" in p and len(p) == 1
    p.close()
    p2 = PoisonList(pp)  # reload across restarts
    assert "x" in p2 and p2.record("x")["attempts"] == 3
    mem = PoisonList()  # path=None: in-memory only
    mem.add("y", error="e", attempts=1)
    assert "y" in mem


def test_watchdog_deadline_call_and_arm():
    import time

    w = Watchdog(0.15)
    assert w.call(lambda: 42, "quick") == 42
    with pytest.raises(WatchdogTimeout):
        w.call(lambda: time.sleep(2.0), "hang")
    assert w.trips == 1
    w.arm("k", "step")
    assert not w.expired()
    time.sleep(0.2)
    exp = w.expired()
    assert exp and exp[0][0] == "k" and exp[0][1] == "step"
    assert isinstance(w.trip("k", "step", exp[0][2]), WatchdogTimeout)
    assert w.trips == 2 and not w.expired()
    assert 0.01 <= w.poll_s() <= 0.15
    with pytest.raises(ValueError):
        Watchdog(0.0)


def test_breaker_state_machine():
    clock = [0.0]
    b = DispatchBreaker(2, 10.0, clock=lambda: clock[0])
    assert b.allow_stacked(32)
    assert b.record_failure(32) is None  # 1 of 2
    assert b.allow_stacked(32)
    assert b.record_failure(32) == "open" and b.trips == 1
    assert not b.allow_stacked(32)  # degraded to per-user dispatch
    assert b.allow_stacked(64)  # other buckets unaffected
    clock[0] = 11.0
    assert b.allow_stacked(32) and b.state_of(32) == "half_open"  # probe
    assert not b.allow_stacked(32)  # one probe at a time
    assert b.record_failure(32) == "open"  # probe failed: re-open
    clock[0] = 22.0
    assert b.allow_stacked(32)
    assert b.record_success(32) == "close"  # probe succeeded: recovered
    assert b.allow_stacked(32) and b.state_of(32) == "closed"
    # a success resets the consecutive-failure count
    assert b.record_failure(32) is None
    assert b.record_success(32) is None
    assert b.record_failure(32) is None
    with pytest.raises(ValueError):
        DispatchBreaker(0)


def test_backoff_delay_schedule_and_jitter():
    import numpy as np

    assert backoff_delay(0, base_delay=0.1, max_delay=2.0) == 0.1
    assert backoff_delay(3, base_delay=0.1, max_delay=2.0) == 0.8
    assert backoff_delay(9, base_delay=0.1, max_delay=2.0) == 2.0  # capped
    rng = np.random.default_rng(0)
    ds = [backoff_delay(1, base_delay=0.1, max_delay=2.0, rng=rng)
          for _ in range(20)]
    assert all(0.1 <= d < 0.3 for d in ds)  # jitter in [0.5, 1.5)x
    assert len(set(ds)) > 1
    # seeded: the schedule replays
    rng2 = np.random.default_rng(0)
    assert ds[0] == backoff_delay(1, base_delay=0.1, max_delay=2.0,
                                  rng=rng2)


# -- restart recovery ------------------------------------------------------


def _min2(cfg):
    """min_members=2 survives committee reloads (the config floor is
    re-applied per session), so an injected member fault exhausts the
    2-member committee on EVERY attempt — the terminal-failure trigger."""
    return dataclasses.replace(cfg, min_members=2)


def _seq_baselines(tmp_path, cfg, specs, committee_fn=_committee):
    seq = []
    for seed, uid, n in specs:
        data = _user_data(seed, uid, n_songs=n)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq.append(ALLoop(cfg).run_user(committee_fn(data), data, str(p)))
    return seq


def _entries(tmp_path, cfg, specs, committee_fn=_committee):
    """Serve entries over the persistent ``serve_*`` workspaces: a fresh
    workspace gets a fresh committee, a restarted one (al_state.json from
    the killed run) resumes from its own files — exactly what the CLI's
    restart path does via ``workspace.create_user``/``load_committee``."""
    out = []
    for seed, uid, n in specs:
        data = _user_data(seed, uid, n_songs=n)
        fp = tmp_path / f"serve_{uid}"
        fp.mkdir(exist_ok=True)
        if (fp / "al_state.json").exists():
            committee = workspace.load_committee(str(fp))
        else:
            committee = committee_fn(data)
        out.append(FleetUser(
            uid, committee, data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp))))
    return out


def _restart_drill(tmp_path, cfg, specs, rule, *, target_live=2,
                   entries_fn=None, scheduler_kw=None):
    """Kill a serving run at ``rule``'s boundary, restart from the
    journal, return ``{user: last result}`` over both segments plus the
    second segment's report.  ``entries_fn``/``scheduler_kw`` let modes
    with non-default committees (qbdc's CNN) ride the same drill."""
    jpath = str(tmp_path / "serve_journal.jsonl")
    entries_fn = entries_fn or _entries
    scheduler_kw = scheduler_kw or {}
    done: dict = {}

    def on_result(rec):
        done[rec["user"]] = rec

    with faults.inject(rule) as inj:
        journal = AdmissionJournal(jpath)
        sched = FleetScheduler(cfg, report=FleetReport(),
                               scoring_by_width=True, **scheduler_kw)
        server = FleetServer(sched, ServeConfig(target_live=target_live),
                             journal=journal)
        with pytest.raises(InjectedKill):
            server.serve(iter(entries_fn(tmp_path, cfg, specs)),
                         on_result=on_result)
        assert inj.fired, f"{rule.point} never fired"
        journal.close()

    journal = AdmissionJournal(jpath)
    assert journal.recovered
    order = journal.state.recovery_order([uid for _, uid, _ in specs])
    emap = {e.user_id: e for e in entries_fn(tmp_path, cfg, specs)}
    report = FleetReport()
    sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                           **scheduler_kw)
    server = FleetServer(sched, ServeConfig(target_live=target_live),
                         journal=journal)
    server.serve(iter(emap[u] for u in order), on_result=on_result)
    journal.close()
    return done, report


@pytest.mark.slow
def test_serve_restart_from_journal_loses_no_user(tmp_path):
    """THE acceptance pin: a server killed at the first
    ``finish`` journal append — after 1 of 3 users finished — restarted
    from ``serve_journal.jsonl`` finishes every submitted user with
    results bit-identical to uninterrupted sequential runs.  The journal
    ends with all three users finished.  (Demoted to slow in PR 9's
    tier-1 budget trade: the kill-at-first-finish restart mechanism
    stays tier-1 via the FUSED-arm cross-arm case in
    ``tests/test_fused_step.py``, and this case runs in
    ``scripts/fault_matrix.sh``.)"""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(100 + i, f"u{i}", 30) for i in range(3)]
    seq = _seq_baselines(tmp_path, cfg, specs)
    # appends 1-5: enqueue x3 + admit x2 (target 2, lazy pull); append 6
    # is the first finish — the user was persisted by on_result but dies
    # un-journaled, so the restart re-admits and re-finishes it
    # idempotently from its final workspace
    done, report = _restart_drill(
        tmp_path, cfg, specs,
        FaultRule("serve.journal.append", "kill", at=6))
    assert sorted(done) == [uid for _, uid, _ in specs]
    for s, (_, uid, _) in zip(seq, specs):
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] == s["trajectory"]
    assert any(e["event"] == "journal_recover" for e in report.events)
    st = AdmissionJournal(str(tmp_path / "serve_journal.jsonl")).state
    assert st.finished == {uid for _, uid, _ in specs}
    assert not st.pending


@pytest.mark.slow
def test_serve_restart_qbdc_loses_no_user(tmp_path):
    """The qbdc restart pin (acceptance; ~38 s — demoted to slow to pay
    for the ISSUE 8 fused-step tier-1 cases, which include an mc serve
    restart on the now-default fused arm and a slow qbdc fused restart in
    ``tests/test_fused_step.py``; ``scripts/fault_matrix.sh`` still runs
    this one): a dropout-committee serve run
    killed at the first completion collection, restarted from the
    journal, finishes every user BIT-IDENTICALLY to uninterrupted
    sequential runs — the K mask keys fold from the checkpointed PRNG
    stream, so neither the workspace resume nor the journal re-admission
    perturbs the committee."""
    from tests.test_acquire import (
        TINY_CNN,
        TINY_TC,
        _cnn_committee,
        _cnn_data,
    )

    cfg = dataclasses.replace(_cfg(mode="qbdc", epochs=2, queries=3),
                              qbdc_k=6)
    specs = [(100 + i, f"u{i}", 8) for i in range(2)]
    seq = []
    for seed, uid, n in specs:
        data = _cnn_data(seed, uid, n_songs=n)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq.append(ALLoop(cfg, retrain_epochs=1).run_user(
            _cnn_committee(data), data, str(p)))

    def entries(tmp_path, cfg, specs):
        out = []
        for seed, uid, n in specs:
            data = _cnn_data(seed, uid, n_songs=n)
            fp = tmp_path / f"serve_{uid}"
            fp.mkdir(exist_ok=True)
            if (fp / "al_state.json").exists():
                committee = workspace.load_committee(str(fp), TINY_CNN,
                                                     TINY_TC)
            else:
                committee = _cnn_committee(data)
            out.append(FleetUser(
                uid, committee, data, str(fp), seed=cfg.seed,
                committee_factory=lambda fp=fp: workspace.load_committee(
                    str(fp), TINY_CNN, TINY_TC)))
        return out

    done, report = _restart_drill(
        tmp_path, cfg, specs, FaultRule("serve.collect", "kill", at=1),
        entries_fn=entries, scheduler_kw={"retrain_epochs": 1})
    assert sorted(done) == [uid for _, uid, _ in specs]
    for s, (_, uid, _) in zip(seq, specs):
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] == s["trajectory"]
    assert any(e["event"] == "journal_recover" for e in report.events)


@pytest.mark.slow
@pytest.mark.parametrize("point,at", [
    ("serve.admit", 2),           # between queue pop and durable admit
    ("serve.journal.append", 4),  # mid-admission (the admit record)
    ("serve.journal.append", 6),  # the first finish record
    ("serve.collect", 1),         # engine done, finish not yet journaled
    ("serve.dispatch", 2),        # mid device dispatch
], ids=lambda v: str(v))
def test_serve_kill_matrix_restart_loses_no_user(tmp_path, point, at):
    """Kill-at-every-serve-boundary: wherever the server dies, a restart
    from the journal serves every submitted user to sequential-identical
    results."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(100 + i, f"u{i}", 30) for i in range(3)]
    seq = _seq_baselines(tmp_path, cfg, specs)
    done, _ = _restart_drill(tmp_path, cfg, specs,
                             FaultRule(point, "kill", at=at))
    assert sorted(done) == [uid for _, uid, _ in specs]
    for s, (_, uid, _) in zip(seq, specs):
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] == s["trajectory"]


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["mc", "hc", "mix", "rand"])
def test_serve_restart_matrix_all_modes(tmp_path, mode):
    """Acceptance: restart recovery is bit-identical in all four
    acquisition modes (k=1 of N=3 users finished at the kill)."""
    cfg = _cfg(mode=mode, epochs=2)
    specs = [(100 + i, f"u{i}", 30) for i in range(3)]
    seq = _seq_baselines(tmp_path, cfg, specs)
    done, _ = _restart_drill(
        tmp_path, cfg, specs,
        FaultRule("serve.journal.append", "kill", at=6))
    assert sorted(done) == [uid for _, uid, _ in specs]
    for s, (_, uid, _) in zip(seq, specs):
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] == s["trajectory"]
        assert done[uid]["result"]["final_mean_f1"] == s["final_mean_f1"]


# -- watchdog / backoff / poison / breaker drills --------------------------


@pytest.mark.slow
def test_serve_watchdog_evicts_hung_host_step(tmp_path):
    """An injected straggler (pool.score delay far past the deadline)
    trips the watchdog: the hung step is abandoned, the session evicted
    and resumed from its workspace, and the user still finishes with the
    sequential trajectory."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(103, "h", 30)]
    seq = _seq_baselines(tmp_path, cfg, specs)
    with faults.inject(FaultRule("pool.score", "delay", at=2,
                                 delay_s=1.5)):
        report = FleetReport()
        # 2 host workers so the zombie (the abandoned sleeping step)
        # cannot starve the resumed session's own host steps
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                               host_workers=2)
        server = FleetServer(sched, ServeConfig(target_live=1,
                                                watchdog_s=0.3))
        recs = server.serve(iter(_entries(tmp_path, cfg, specs)))
    evs = [e["event"] for e in report.events]
    assert "watchdog_evict" in evs and "resume" in evs
    assert sched.watchdog.trips >= 1
    assert recs[0]["error"] is None
    assert recs[0]["result"]["trajectory"] == seq[0]["trajectory"]
    assert report.summary(cohort=1)["watchdog_evictions"] >= 1


@pytest.mark.slow
def test_serve_backoff_readmission_recovers(tmp_path):
    """A user whose session fails terminally (initial run AND in-engine
    resume both exhaust the committee) re-enters the queue with backoff
    and succeeds on its second admission — sequential-identical."""
    cfg = _min2(_cfg(mode="mc", epochs=2))
    specs = [(100, "v", 30)]
    seq = _seq_baselines(
        tmp_path, cfg, specs,
        committee_fn=lambda d: _committee(d, sgd_name="sgd.victim"))
    entries = _entries(
        tmp_path, cfg, specs,
        committee_fn=lambda d: _committee(d, sgd_name="sgd.victim"))
    with faults.inject(FaultRule("member.retrain", "raise", at=1, times=2,
                                 member="sgd.victim")) as inj:
        report = FleetReport()
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True)
        server = FleetServer(sched, ServeConfig(
            target_live=1, failure_budget=3,
            backoff_base_s=0.01, backoff_max_s=0.05))
        recs = server.serve(iter(entries))
    assert inj.fired
    evs = [e["event"] for e in report.events]
    # evict -> in-engine resume -> evict -> terminal -> requeue -> admit
    assert evs.count("requeue") == 1 and evs.count("admit") == 2
    assert recs[0]["error"] is None
    assert recs[0]["result"]["trajectory"] == seq[0]["trajectory"]
    assert report.summary(cohort=1)["requeues"] == 1
    assert report.users_failed == 0


@pytest.mark.slow
def test_serve_poison_after_budget_then_skipped(tmp_path):
    """A user that fails on EVERY admission exhausts its failure budget,
    lands in the persisted poison list (terminal reason + attempts in the
    metrics stream), and never stalls admission — a healthy user behind
    it finishes normally.  A later server with the same poison list skips
    the user outright."""
    cfg = _min2(_cfg(mode="mc", epochs=2))
    good_specs = [(101, "w", 30)]
    seq = _seq_baselines(tmp_path, cfg, good_specs)
    bad_specs = [(102, "pz", 30)]
    bad = _entries(tmp_path, cfg, bad_specs,
                   committee_fn=lambda d: _committee(
                       d, sgd_name="sgd.victim"))
    good = _entries(tmp_path, cfg, good_specs)
    ppath = str(tmp_path / "serve_poison.jsonl")
    with faults.inject(FaultRule("member.retrain", "raise", at=1, times=-1,
                                 member="sgd.victim")):
        report = FleetReport()
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True)
        server = FleetServer(
            sched,
            ServeConfig(target_live=1, failure_budget=2,
                        backoff_base_s=0.01, backoff_max_s=0.02),
            poison=PoisonList(ppath))
        recs = server.serve(iter(bad + good))
    by = {r["user"]: r for r in recs}
    assert by["pz"]["error"] is not None
    assert by["w"]["error"] is None
    assert by["w"]["result"]["trajectory"] == seq[0]["trajectory"]
    s = report.summary(cohort=1)
    assert s["users_poisoned"] == 1 and s["requeues"] == 1
    assert s["users_failed"] == 1
    pev = [e for e in report.events if e["event"] == "poison"]
    assert pev and pev[0]["attempts"] == 2 and pev[0]["error"]
    fev = [e for e in report.events if e["event"] == "user_failed"]
    assert fev and "attempts" in fev[0] and fev[0]["error"]
    # a fresh server (restart) skips the poisoned user via the persisted
    # list: no admission, no result, an explicit skip event
    report2 = FleetReport()
    sched2 = FleetScheduler(cfg, report=report2, scoring_by_width=True)
    server2 = FleetServer(sched2, ServeConfig(target_live=1),
                          poison=PoisonList(ppath))
    recs2 = server2.serve(iter(_entries(tmp_path, cfg, bad_specs)))
    assert recs2 == []
    assert any(e["event"] == "skip_poisoned" for e in report2.events)


@pytest.mark.slow
def test_serve_breaker_opens_degrades_and_recovers(tmp_path):
    """Stacked-dispatch failures open the bucket's breaker: the batch
    falls back to per-user dispatch (nobody evicted), the width stays
    degraded through the cooldown, then a half-open probe restores
    stacked dispatch — and every trajectory matches sequential."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(104, "b0", 30), (105, "b1", 30)]
    seq = _seq_baselines(tmp_path, cfg, specs)
    with faults.inject(FaultRule("serve.dispatch", "transient", at=1,
                                 times=1)) as inj:
        report = FleetReport()
        breaker = DispatchBreaker(1, 0.0001)  # trip fast, recover fast
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                               breaker=breaker, batch_window_s=5.0)
        server = FleetServer(sched, ServeConfig(target_live=2))
        recs = server.serve(iter(_entries(tmp_path, cfg, specs)))
    assert inj.fired
    evs = [e["event"] for e in report.events]
    assert "dispatch_failed" in evs and "breaker_open" in evs
    assert "breaker_probe" in evs and "breaker_close" in evs
    assert "evict" not in evs  # the fallback isolated the failure
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]
    assert breaker.trips == 1 and breaker.summary() == {}
    s = report.summary(cohort=2)
    assert s["breaker_trips"] == 1 and s["dispatch_failures"] == 1


@pytest.mark.slow
def test_serve_watchdog_expiry_counts_toward_breaker(tmp_path):
    """Watchdog × breaker interaction: a STACKED dispatch that blows the
    watchdog deadline (injected delay far past it) must count toward the
    bucket's breaker exactly like an exception-failed dispatch — the
    breaker opens at threshold 1 — and the batch must still fall back to
    per-user dispatch with nobody evicted and sequential-identical
    results."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(110, "wb0", 30), (111, "wb1", 30)]
    seq = _seq_baselines(tmp_path, cfg, specs)
    with faults.inject(FaultRule("serve.dispatch", "delay", at=1,
                                 delay_s=3.0)) as inj:
        report = FleetReport()
        breaker = DispatchBreaker(1, 60.0)  # one failure opens; no probe
        # batch_window_s phase-aligns both sessions so the delayed (and
        # watchdog-expired) dispatch is the STACKED one; the 1s deadline
        # clears legit host steps and the warm single-user fns by a wide
        # margin on the throttled box
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                               breaker=breaker, batch_window_s=5.0,
                               watchdog=Watchdog(1.0))
        server = FleetServer(sched, ServeConfig(target_live=2))
        recs = server.serve(iter(_entries(tmp_path, cfg, specs)))
    assert inj.fired
    evs = [e["event"] for e in report.events]
    # the expiry was recorded as a dispatch failure AND tripped the
    # breaker: the width is degraded, not probed (cooldown far away)
    assert "dispatch_failed" in evs and "breaker_open" in evs
    assert "evict" not in evs  # per-user fallback isolated the expiry
    assert sched.watchdog.trips >= 1
    assert breaker.trips == 1 and breaker.state_of(32) == "open"
    failed = next(e for e in report.events
                  if e["event"] == "dispatch_failed")
    assert "WatchdogTimeout" in failed["error"]
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]
    s = report.summary(cohort=2)
    assert s["breaker_trips"] == 1 and s["dispatch_failures"] == 1


@pytest.mark.slow
def test_serve_dispatch_error_isolates_single_session(tmp_path):
    """A per-user dispatch failure evicts ONLY that session (generator
    error path → resume → backoff re-admission when resumes exhaust);
    with the rule spent, the user recovers to the sequential result."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(106, "s", 30)]
    seq = _seq_baselines(tmp_path, cfg, specs)
    with faults.inject(FaultRule("serve.dispatch", "raise", at=1,
                                 times=2)) as inj:
        report = FleetReport()
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True)
        server = FleetServer(sched, ServeConfig(
            target_live=1, failure_budget=3,
            backoff_base_s=0.01, backoff_max_s=0.05))
        recs = server.serve(iter(_entries(tmp_path, cfg, specs)))
    assert inj.fired
    evs = [e["event"] for e in report.events]
    assert "dispatch_session_error" in evs
    assert recs[0]["error"] is None
    assert recs[0]["result"]["trajectory"] == seq[0]["trajectory"]


@pytest.mark.slow
def test_serve_flaky_mix_smoke(tmp_path):
    """The serve_fault_bench fast subset: a 2-user mix with one flaky
    user (member fault absorbed by evict+resume) finishes everyone with
    sequential-identical results and records the recovery telemetry.
    (Demoted to slow in PR 11's tier-1 budget trade against the new SLO
    planner tier-1 cases — the evict+resume+backoff mechanisms stay
    tier-1-adjacent via the SLO smoke and pure-host units, and this case
    still runs in ``scripts/fault_matrix.sh``.)"""
    cfg = _min2(_cfg(mode="mc", epochs=2))
    flaky = lambda d: _committee(d, sgd_name="sgd.flaky")  # noqa: E731
    specs = [(107, "f", 30), (108, "ok", 30)]
    seq = [_seq_baselines(tmp_path, cfg, specs[:1], committee_fn=flaky)[0],
           _seq_baselines(tmp_path, cfg, specs[1:])[0]]
    entries = (_entries(tmp_path, cfg, specs[:1], committee_fn=flaky)
               + _entries(tmp_path, cfg, specs[1:]))
    with faults.inject(FaultRule("member.retrain", "raise", at=1,
                                 member="sgd.flaky")) as inj:
        report = FleetReport()
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True)
        server = FleetServer(sched, ServeConfig(
            target_live=2, failure_budget=2,
            backoff_base_s=0.01, backoff_max_s=0.05, watchdog_s=30.0))
        recs = server.serve(iter(entries))
    assert inj.fired
    by = {r["user"]: r for r in recs}
    for s, (_, uid, _) in zip(seq, specs):
        assert by[uid]["error"] is None
        assert by[uid]["result"]["trajectory"] == s["trajectory"]
    s = report.summary(cohort=2)
    assert s["evictions"] >= 1 and s["users_failed"] == 0
