"""Committee orchestration: FramePool aggregation, mixed host+device probs,
checkpoint round-trip."""

import jax
import pytest
import numpy as np

from consensus_entropy_tpu.config import CNNConfig, NUM_CLASSES, TrainConfig
from consensus_entropy_tpu.data.audio import DeviceWaveformStore
from consensus_entropy_tpu.labels import one_hot_np
from consensus_entropy_tpu.models import short_cnn
from consensus_entropy_tpu.models.committee import CNNMember, Committee, FramePool
from consensus_entropy_tpu.models.sklearn_members import GNBMember, SGDMember
from consensus_entropy_tpu.utils.checkpoint import load_variables, save_variables

TINY = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)


def _frame_pool(rng, n_songs=10, frames_per=(3, 8), f=12):
    rows, sids = [], []
    for i in range(n_songs):
        k = int(rng.integers(*frames_per))
        rows.append(rng.standard_normal((k, f)).astype(np.float32))
        sids += [f"song{i}"] * k
    return FramePool(np.vstack(rows), sids)


def test_frame_pool_groupby_mean_parity(rng):
    import pandas as pd

    X = rng.standard_normal((50, 4)).astype(np.float32)
    sids = [f"s{i % 7}" for i in range(50)]
    pool = FramePool(X, sids)
    df = pd.DataFrame(X.copy())
    df["s_id"] = sids
    want = df.groupby("s_id").mean().sort_index()
    got = pool.mean_by_song(pool.X)
    np.testing.assert_array_equal(pool.song_ids, list(want.index))
    np.testing.assert_allclose(got, want.values, rtol=1e-5)


def test_rows_for_songs(rng):
    pool = _frame_pool(rng)
    rows = pool.rows_for_songs(["song2", "song5"])
    i2 = pool.song_ids.index("song2")
    i5 = pool.song_ids.index("song5")
    assert len(rows) == pool.counts[i2] + pool.counts[i5]


def _committee(rng, n_cnn=2):
    Xf = rng.standard_normal((120, 12)).astype(np.float32)
    yf = rng.integers(0, 4, size=120)
    host = [GNBMember().fit(Xf, yf), SGDMember(seed=0).fit(Xf, yf)]
    cnns = [CNNMember(f"cnn{i}",
                      short_cnn.init_variables(jax.random.key(i), TINY), TINY)
            for i in range(n_cnn)]
    return Committee(host, cnns, TINY, TrainConfig(batch_size=2))


def test_pool_probs_shape_and_blocks(rng):
    com = _committee(rng)
    pool = _frame_pool(rng, n_songs=8, f=12)
    waves = {s: rng.standard_normal(9000).astype(np.float32)
             for s in pool.song_ids}
    store = DeviceWaveformStore(waves, TINY.input_length)
    probs = np.asarray(com.pool_probs(pool, store, pool.song_ids,
                                      jax.random.key(0)))
    assert probs.shape == (4, 8, NUM_CLASSES)
    # host blocks are proper distributions; CNN blocks are sigmoid scores
    np.testing.assert_allclose(probs[2:].sum(axis=-1), 1.0, atol=1e-4)
    assert ((probs[:2] > 0) & (probs[:2] < 1)).all()


def test_pool_probs_pad_to_contract(rng):
    """``pad_to`` staging: the first n columns must equal the exact-width
    call bit-for-bit (same key → same crops), the block must be exactly
    (M, pad_to, C), and host tails must be well-formed rows."""
    com = _committee(rng)
    pool = _frame_pool(rng, n_songs=8, f=12)
    waves = {s: rng.standard_normal(9000).astype(np.float32)
             for s in pool.song_ids}
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = pool.song_ids[:5]
    key = jax.random.key(3)
    exact = np.asarray(com.pool_probs(pool, store, ids, key))
    padded = np.asarray(com.pool_probs(pool, store, ids, key, pad_to=12))
    assert padded.shape == (4, 12, NUM_CLASSES)
    np.testing.assert_array_equal(padded[:, :5], exact)
    # host-member staging columns are repeats of the last live column
    np.testing.assert_array_equal(
        padded[2:, 5:], np.repeat(padded[2:, 4:5], 7, axis=1))
    # pure-host committees stage on host at the padded width too
    com2 = _committee(rng, n_cnn=0)
    p2 = com2.pool_probs(pool, None, ids, key, pad_to=12)
    assert isinstance(p2, np.ndarray) and p2.shape == (2, 12, NUM_CLASSES)
    import pytest

    with pytest.raises(ValueError, match="pad_to"):
        com.pool_probs(pool, store, ids, key, pad_to=3)


def test_host_only_committee(rng):
    com = _committee(rng, n_cnn=0)
    pool = _frame_pool(rng, n_songs=6, f=12)
    probs = np.asarray(com.pool_probs(pool, None, pool.song_ids,
                                      jax.random.key(0)))
    assert probs.shape == (2, 6, NUM_CLASSES)


def test_committee_update_and_retrain(rng):
    com = _committee(rng, n_cnn=1)
    pool = _frame_pool(rng, n_songs=6, f=12)
    waves = {s: rng.standard_normal(9500).astype(np.float32)
             for s in pool.song_ids}
    store = DeviceWaveformStore(waves, TINY.input_length)
    Xb = rng.standard_normal((10, 12)).astype(np.float32)
    yb = rng.integers(0, 4, size=10)
    com.update_host(Xb, yb)
    ids = pool.song_ids[:4]
    y = one_hot_np(rng.integers(0, 4, size=4))
    before = np.asarray(com.cnn_members[0].variables["params"]
                        ["dense2"]["kernel"]).copy()
    # enough epochs for some epoch's score = 1 - val_loss to clear the
    # reference's 0-init best gate (amg_test.py:295) on random data
    hists = com.retrain_cnns(store, ids, y, ids, y, jax.random.key(1),
                             n_epochs=8)
    assert len(hists) == 1 and len(hists[0]) == 8
    assert any(h["improved"] for h in hists[0]), hists[0]
    after = np.asarray(com.cnn_members[0].variables["params"]
                       ["dense2"]["kernel"])
    assert not np.allclose(before, after)


def test_variables_checkpoint_roundtrip(tmp_path, rng):
    v = short_cnn.init_variables(jax.random.key(0), TINY)
    path = str(tmp_path / "cnn.msgpack")
    save_variables(path, v, meta={"name": "cnn0"})
    v2, meta = load_variables(path)
    assert meta["name"] == "cnn0"
    x = rng.standard_normal((2, TINY.input_length)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(short_cnn.apply_infer(v, x, TINY)),
        np.asarray(short_cnn.apply_infer(v2, x, TINY)), rtol=1e-6)


def test_committee_save(tmp_path, rng):
    com = _committee(rng, n_cnn=1)
    com.save(str(tmp_path / "user0"))
    import os

    files = sorted(os.listdir(tmp_path / "user0"))
    assert any(f.startswith("classifier_cnn") for f in files)
    assert any(f.startswith("classifier_gnb") for f in files)
    assert any(f.startswith("classifier_sgd") for f in files)


def test_host_scoring_restricted_to_live_songs(rng):
    """Host members score ONLY the live songs' frames (amg_test.py:435
    scores the shrinking X_train), and the per-song means match the
    full-table-then-slice result exactly."""
    com = _committee(rng, n_cnn=0)
    pool = _frame_pool(rng, n_songs=8, f=12)
    live = pool.song_ids[::2] + pool.song_ids[1:2]  # subset, mixed order
    probs = np.asarray(com.pool_probs(pool, None, live, jax.random.key(0)))
    sel = [pool.song_ids.index(s) for s in live]
    for i, m in enumerate(com.host_members):
        full = pool.mean_by_song(m.predict_proba(pool.X))
        np.testing.assert_allclose(probs[i], full[sel], rtol=1e-6)

    # spy member: the frame table it sees must be exactly the live frames
    seen = {}

    class Spy:
        def predict_proba(self, X):
            seen["n"] = len(X)
            return np.full((len(X), NUM_CLASSES), 0.25, np.float32)

    com.host_members.append(Spy())
    com.pool_probs(pool, None, live, jax.random.key(0))
    assert seen["n"] == sum(pool.count_of(s) for s in live)
    assert seen["n"] < len(pool.X)


def test_jit_programs_shared_across_committee_instances(rng):
    # A fresh Committee is built per user in the AL run; its inference
    # programs must be the SAME process-wide jit objects (module-level
    # lru_cache keyed by the frozen config), or every user re-traces and
    # re-compiles the full-geometry forward (~15-30 s/user on the TPU —
    # the warm user's entire first-iteration `score` in ITERATION_r04).
    c1 = _committee(rng)
    c2 = _committee(rng)
    assert c1._infer is c2._infer
    assert c1._infer_windows is c2._infer_windows
    # ...and a different architecture must NOT share programs
    other = CNNConfig(n_channels=8, n_mels=32, n_layers=5, input_length=8192)
    cnns = [CNNMember("c", short_cnn.init_variables(jax.random.key(9), other),
                      other)]
    c3 = Committee([], cnns, other, TrainConfig(batch_size=2))
    assert c3._infer is not c1._infer


def test_epoch_programs_shared_across_trainer_instances():
    # Same contract for the retrain programs: per-user CNNTrainer instances
    # (one per committee) must hit one module-level cache — a per-instance
    # cache cost the warm user ~104 s of re-trace+re-compile on its first
    # retrain_cnn phase (ITERATION_r04).
    from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer

    tc = TrainConfig(batch_size=2)
    t1 = CNNTrainer(TINY, tc)
    t2 = CNNTrainer(TINY, tc)
    assert t1._epoch_fn("adam", 4, 2, 2) is t2._epoch_fn("adam", 4, 2, 2)
    assert (t1._epoch_fn_many("adam", 4, 2, 2)
            is t2._epoch_fn_many("adam", 4, 2, 2))
    # distinct shape keys stay distinct programs
    assert t1._epoch_fn("adam", 6, 2, 2) is not t1._epoch_fn("adam", 4, 2, 2)


def test_scoring_fns_shared_across_acquirers():
    from consensus_entropy_tpu.ops import scoring

    assert (scoring.make_scoring_fns(k=10)
            is scoring.make_scoring_fns(k=10))
    # the wrapper normalizes the signature before the cache: an explicit
    # default must not create a duplicate set of jit programs
    assert (scoring.make_scoring_fns(k=10)
            is scoring.make_scoring_fns(k=10, tie_break="fast"))
    assert (scoring.make_scoring_fns(k=10)
            is not scoring.make_scoring_fns(k=5))


def test_crop_forward_sliced_in_buckets(rng):
    # The crop forward dispatches in bucket-wide sub-slices so a big pool
    # can never exceed HBM (a single >=1536-crop dispatch at full geometry
    # fails to COMPILE on v5e: 23.3 GB layer-1 allocation).  Contract:
    # (a) crops are sampled at full width first, so a 300-song pool's
    # first-256 columns equal a 256-song pool's columns exactly (threefry
    # prefix-stability + per-row inference independence); (b) every slice
    # is exactly bucket-wide, so ONE forward program serves any pool size.
    cnns = [CNNMember("c0",
                      short_cnn.init_variables(jax.random.key(3), TINY),
                      TINY)]
    com = Committee([], cnns, TINY, TrainConfig(batch_size=2))
    songs = [f"s{i:03d}" for i in range(300)]
    waves = {s: rng.standard_normal(9000).astype(np.float32)
             for s in songs}
    store = DeviceWaveformStore(waves, TINY.input_length)
    size0 = com._infer._cache_size()
    big = np.asarray(com.predict_songs_cnn(store, songs, jax.random.key(7)))
    small = np.asarray(com.predict_songs_cnn(store, songs[:256],
                                             jax.random.key(7)))
    assert big.shape == (1, 300, NUM_CLASSES)
    np.testing.assert_allclose(big[:, :256], small, rtol=1e-6, atol=1e-6)
    # both calls dispatch only bucket-wide (256) batches -> at most one
    # new program regardless of pool width
    assert com._infer._cache_size() <= size0 + 1


def test_crop_forward_sliced_under_pool_mesh(rng):
    # The bucket-sliced crop forward must also hold on a pool-sharded
    # mesh: bucket = lcm(256, n_shards), so every sub-slice stays
    # shard-divisible and the sharded program is reused across slices.
    # A >bucket pool (300 songs -> two 256-wide slices on the 8-device
    # virtual mesh) must score identically to the single-device path.
    from consensus_entropy_tpu.parallel.mesh import make_pool_mesh

    cnns = [CNNMember("c0",
                      short_cnn.init_variables(jax.random.key(3), TINY),
                      TINY)]
    songs = [f"s{i:03d}" for i in range(300)]
    waves = {s: rng.standard_normal(9000).astype(np.float32)
             for s in songs}
    store = DeviceWaveformStore(waves, TINY.input_length)
    single = Committee([], cnns, TINY, TrainConfig(batch_size=2))
    ref = np.asarray(single.predict_songs_cnn(store, songs,
                                              jax.random.key(7)))
    meshed = Committee([], cnns, TINY, TrainConfig(batch_size=2),
                       mesh=make_pool_mesh())
    got = np.asarray(meshed.predict_songs_cnn(store, songs,
                                              jax.random.key(7)))
    assert got.shape == (1, 300, NUM_CLASSES)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_begin_save_skips_clean_members(tmp_path, rng):
    """Per-iteration checkpoint traffic: a CNN member whose variables were
    not rebound since its last snapshot is NOT re-fetched or re-written
    when the live workspace already holds its file (promote leaves
    non-staged files in place, so the old file stays exactly current)."""
    import os

    from consensus_entropy_tpu.al import workspace

    com = _committee(rng, n_cnn=2)
    live = tmp_path / "user0"
    com.save(str(live))  # fresh dir: everything written
    loaded = workspace.load_committee(str(live), TINY,
                                      TrainConfig(batch_size=2))
    assert all(not m.ckpt_dirty for m in loaded.cnn_members)

    stage = tmp_path / "stage1"
    loaded.begin_save(str(stage), reuse_dir=str(live))()
    staged = sorted(os.listdir(stage))
    assert not any(f.startswith("classifier_cnn") for f in staged)
    assert any(f.startswith("classifier_gnb") for f in staged)

    # rebinding one member's variables marks it dirty -> it (and only it)
    # is written by the next checkpoint
    loaded.cnn_members[0].variables = loaded.cnn_members[0].variables
    stage2 = tmp_path / "stage2"
    loaded.begin_save(str(stage2), reuse_dir=str(live))()
    cnn_files = [f for f in os.listdir(stage2)
                 if f.startswith("classifier_cnn")]
    assert cnn_files == [f"classifier_cnn.{loaded.cnn_members[0].name}"
                         ".msgpack"]
    assert not loaded.cnn_members[0].ckpt_dirty
    # without reuse_dir (pretrain-registry save) everything is written
    stage3 = tmp_path / "stage3"
    loaded.begin_save(str(stage3))()
    assert len([f for f in os.listdir(stage3)
                if f.startswith("classifier_cnn")]) == 2


def test_bf16_checkpoint_roundtrip(tmp_path, rng):
    """dtype='bfloat16' halves the checkpoint fetch; restore comes back
    f32 within bf16 rounding and scores within the committed bf16 gate's
    tolerance."""
    com = _committee(rng, n_cnn=1)
    d = tmp_path / "user0"
    com.begin_save(str(d), dtype="bfloat16")()
    m2 = CNNMember.load(
        str(d / f"classifier_cnn.{com.cnn_members[0].name}.msgpack"), TINY)
    assert not m2.ckpt_dirty
    v1, v2 = com.cnn_members[0].variables, m2.variables
    for a, b in zip(jax.tree.leaves(v1), jax.tree.leaves(v2)):
        b = np.asarray(b)
        assert b.dtype == np.float32
        np.testing.assert_allclose(np.asarray(a), b, rtol=1 / 128, atol=1e-6)
    x = rng.standard_normal((3, TINY.input_length)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(short_cnn.apply_infer(v1, x, TINY)),
        np.asarray(short_cnn.apply_infer(v2, x, TINY)), atol=2e-2)


def test_retrain_keeps_clean_member_unbound(tmp_path, rng):
    """A retrain in which NO epoch improves (score = 1 - val_loss never
    clears the 0-init gate) returns the incoming weights; the member must
    keep its old tree and stay checkpoint-clean so the next begin_save
    skips its fetch."""
    com = _committee(rng, n_cnn=1)
    m = com.cnn_members[0]
    # bias the head to predict ~1 everywhere, then validate against
    # all-zero targets: val BCE ~= 10 >> 1 every epoch -> never improves
    v = m.variables
    v["params"]["dense2"]["bias"] = v["params"]["dense2"]["bias"] + 10.0
    m.variables = v
    com.save(str(tmp_path / "live"))
    m.ckpt_dirty = False  # as after a load from the live workspace
    old_tree = m.variables
    waves = {f"s{i}": rng.standard_normal(9500).astype(np.float32)
             for i in range(4)}
    store = DeviceWaveformStore(waves, TINY.input_length)
    y_zero = np.zeros((4, NUM_CLASSES), np.float32)
    hists = com.retrain_cnns(store, list(waves), y_zero, list(waves),
                             y_zero, jax.random.key(0), n_epochs=2)
    assert not any(h["improved"] for h in hists[0])
    assert m.variables is old_tree
    assert not m.ckpt_dirty


def test_update_host_gated_restores_hurt_members(rng):
    """Validation-gated host updates (the host analogue of the CNN
    best-checkpoint gate): a poisonous batch is rolled back, a helpful
    one is kept, and the returned map says which happened."""
    Xf = rng.standard_normal((200, 12)).astype(np.float32) \
        + np.eye(4, 12, dtype=np.float32)[rng.integers(0, 4, 200)] * 6
    yf = Xf[:, :4].argmax(1)
    com = _committee(rng, n_cnn=0)
    for m in com.host_members:
        m.fit(Xf[:150], yf[:150])
    X_val, y_val = Xf[150:], yf[150:]
    from consensus_entropy_tpu.al.reporting import weighted_f1

    before = [weighted_f1(y_val, m.predict(X_val))
              for m in com.host_members]
    # poisonous batch: systematically WRONG labels
    kept = com.update_host_gated(Xf[:40], (yf[:40] + 1) % 4, X_val, y_val)
    after = [weighted_f1(y_val, m.predict(X_val))
             for m in com.host_members]
    for b, a, m in zip(before, after, com.host_members):
        if not kept[m.name]:
            assert a == pytest.approx(b)  # rolled back
        else:
            assert a >= b  # kept only because it did not hurt
    # a helpful batch (correct labels) is kept for at least one member
    kept2 = com.update_host_gated(Xf[:40], yf[:40], X_val, y_val)
    assert any(kept2.values())


def test_al_loop_gate_host_updates_flag(rng, tmp_path):
    """ALConfig.gate_host_updates routes the loop's update phase through
    the gated path; a full host-only run completes and never ends below
    its baseline F1 (the gate's guarantee on the gating split)."""
    import dataclasses

    from consensus_entropy_tpu.al.loop import ALLoop, UserData
    from consensus_entropy_tpu.config import ALConfig

    Xf = rng.standard_normal((240, 12)).astype(np.float32)
    centers = rng.standard_normal((4, 12)).astype(np.float32) * 3
    labels, sids = {}, []
    rows = []
    for i in range(60):
        c = int(rng.integers(0, 4))
        sid = f"song{i:03d}"
        labels[sid] = c
        rows.append(centers[c] + rng.standard_normal((4, 12)).astype(np.float32))
        sids += [sid] * 4
    pool = FramePool(np.vstack(rows), sids)
    data = UserData("u0", pool, labels)
    com = _committee(rng, n_cnn=0)
    loop = ALLoop(ALConfig(queries=5, epochs=3, mode="mc", seed=3,
                           gate_host_updates=True))
    res = loop.run_user(com, data, str(tmp_path))
    traj = res["trajectory"]
    assert len(traj) == 4
    # the gate scores on the SAME split and metric the loop evaluates, so
    # a host-only gated run's mean-F1 trajectory is non-decreasing — the
    # assertion an ungated run would not satisfy in general (and the one
    # that actually detects the flag being ignored)
    assert all(b >= a - 1e-9 for a, b in zip(traj, traj[1:])), traj
