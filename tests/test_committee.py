"""Committee orchestration: FramePool aggregation, mixed host+device probs,
checkpoint round-trip."""

import jax
import numpy as np

from consensus_entropy_tpu.config import CNNConfig, NUM_CLASSES, TrainConfig
from consensus_entropy_tpu.data.audio import DeviceWaveformStore
from consensus_entropy_tpu.labels import one_hot_np
from consensus_entropy_tpu.models import short_cnn
from consensus_entropy_tpu.models.committee import CNNMember, Committee, FramePool
from consensus_entropy_tpu.models.sklearn_members import GNBMember, SGDMember
from consensus_entropy_tpu.utils.checkpoint import load_variables, save_variables

TINY = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)


def _frame_pool(rng, n_songs=10, frames_per=(3, 8), f=12):
    rows, sids = [], []
    for i in range(n_songs):
        k = int(rng.integers(*frames_per))
        rows.append(rng.standard_normal((k, f)).astype(np.float32))
        sids += [f"song{i}"] * k
    return FramePool(np.vstack(rows), sids)


def test_frame_pool_groupby_mean_parity(rng):
    import pandas as pd

    X = rng.standard_normal((50, 4)).astype(np.float32)
    sids = [f"s{i % 7}" for i in range(50)]
    pool = FramePool(X, sids)
    df = pd.DataFrame(X.copy())
    df["s_id"] = sids
    want = df.groupby("s_id").mean().sort_index()
    got = pool.mean_by_song(pool.X)
    np.testing.assert_array_equal(pool.song_ids, list(want.index))
    np.testing.assert_allclose(got, want.values, rtol=1e-5)


def test_rows_for_songs(rng):
    pool = _frame_pool(rng)
    rows = pool.rows_for_songs(["song2", "song5"])
    i2 = pool.song_ids.index("song2")
    i5 = pool.song_ids.index("song5")
    assert len(rows) == pool.counts[i2] + pool.counts[i5]


def _committee(rng, n_cnn=2):
    Xf = rng.standard_normal((120, 12)).astype(np.float32)
    yf = rng.integers(0, 4, size=120)
    host = [GNBMember().fit(Xf, yf), SGDMember(seed=0).fit(Xf, yf)]
    cnns = [CNNMember(f"cnn{i}",
                      short_cnn.init_variables(jax.random.key(i), TINY), TINY)
            for i in range(n_cnn)]
    return Committee(host, cnns, TINY, TrainConfig(batch_size=2))


def test_pool_probs_shape_and_blocks(rng):
    com = _committee(rng)
    pool = _frame_pool(rng, n_songs=8, f=12)
    waves = {s: rng.standard_normal(9000).astype(np.float32)
             for s in pool.song_ids}
    store = DeviceWaveformStore(waves, TINY.input_length)
    probs = np.asarray(com.pool_probs(pool, store, pool.song_ids,
                                      jax.random.key(0)))
    assert probs.shape == (4, 8, NUM_CLASSES)
    # host blocks are proper distributions; CNN blocks are sigmoid scores
    np.testing.assert_allclose(probs[2:].sum(axis=-1), 1.0, atol=1e-4)
    assert ((probs[:2] > 0) & (probs[:2] < 1)).all()


def test_pool_probs_pad_to_contract(rng):
    """``pad_to`` staging: the first n columns must equal the exact-width
    call bit-for-bit (same key → same crops), the block must be exactly
    (M, pad_to, C), and host tails must be well-formed rows."""
    com = _committee(rng)
    pool = _frame_pool(rng, n_songs=8, f=12)
    waves = {s: rng.standard_normal(9000).astype(np.float32)
             for s in pool.song_ids}
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = pool.song_ids[:5]
    key = jax.random.key(3)
    exact = np.asarray(com.pool_probs(pool, store, ids, key))
    padded = np.asarray(com.pool_probs(pool, store, ids, key, pad_to=12))
    assert padded.shape == (4, 12, NUM_CLASSES)
    np.testing.assert_array_equal(padded[:, :5], exact)
    # host-member staging columns are repeats of the last live column
    np.testing.assert_array_equal(
        padded[2:, 5:], np.repeat(padded[2:, 4:5], 7, axis=1))
    # pure-host committees stage on host at the padded width too
    com2 = _committee(rng, n_cnn=0)
    p2 = com2.pool_probs(pool, None, ids, key, pad_to=12)
    assert isinstance(p2, np.ndarray) and p2.shape == (2, 12, NUM_CLASSES)
    import pytest

    with pytest.raises(ValueError, match="pad_to"):
        com.pool_probs(pool, store, ids, key, pad_to=3)


def test_host_only_committee(rng):
    com = _committee(rng, n_cnn=0)
    pool = _frame_pool(rng, n_songs=6, f=12)
    probs = np.asarray(com.pool_probs(pool, None, pool.song_ids,
                                      jax.random.key(0)))
    assert probs.shape == (2, 6, NUM_CLASSES)


def test_committee_update_and_retrain(rng):
    com = _committee(rng, n_cnn=1)
    pool = _frame_pool(rng, n_songs=6, f=12)
    waves = {s: rng.standard_normal(9500).astype(np.float32)
             for s in pool.song_ids}
    store = DeviceWaveformStore(waves, TINY.input_length)
    Xb = rng.standard_normal((10, 12)).astype(np.float32)
    yb = rng.integers(0, 4, size=10)
    com.update_host(Xb, yb)
    ids = pool.song_ids[:4]
    y = one_hot_np(rng.integers(0, 4, size=4))
    before = np.asarray(com.cnn_members[0].variables["params"]
                        ["dense2"]["kernel"]).copy()
    # enough epochs for some epoch's score = 1 - val_loss to clear the
    # reference's 0-init best gate (amg_test.py:295) on random data
    hists = com.retrain_cnns(store, ids, y, ids, y, jax.random.key(1),
                             n_epochs=8)
    assert len(hists) == 1 and len(hists[0]) == 8
    assert any(h["improved"] for h in hists[0]), hists[0]
    after = np.asarray(com.cnn_members[0].variables["params"]
                       ["dense2"]["kernel"])
    assert not np.allclose(before, after)


def test_variables_checkpoint_roundtrip(tmp_path, rng):
    v = short_cnn.init_variables(jax.random.key(0), TINY)
    path = str(tmp_path / "cnn.msgpack")
    save_variables(path, v, meta={"name": "cnn0"})
    v2, meta = load_variables(path)
    assert meta["name"] == "cnn0"
    x = rng.standard_normal((2, TINY.input_length)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(short_cnn.apply_infer(v, x, TINY)),
        np.asarray(short_cnn.apply_infer(v2, x, TINY)), rtol=1e-6)


def test_committee_save(tmp_path, rng):
    com = _committee(rng, n_cnn=1)
    com.save(str(tmp_path / "user0"))
    import os

    files = sorted(os.listdir(tmp_path / "user0"))
    assert any(f.startswith("classifier_cnn") for f in files)
    assert any(f.startswith("classifier_gnb") for f in files)
    assert any(f.startswith("classifier_sgd") for f in files)


def test_host_scoring_restricted_to_live_songs(rng):
    """Host members score ONLY the live songs' frames (amg_test.py:435
    scores the shrinking X_train), and the per-song means match the
    full-table-then-slice result exactly."""
    com = _committee(rng, n_cnn=0)
    pool = _frame_pool(rng, n_songs=8, f=12)
    live = pool.song_ids[::2] + pool.song_ids[1:2]  # subset, mixed order
    probs = np.asarray(com.pool_probs(pool, None, live, jax.random.key(0)))
    sel = [pool.song_ids.index(s) for s in live]
    for i, m in enumerate(com.host_members):
        full = pool.mean_by_song(m.predict_proba(pool.X))
        np.testing.assert_allclose(probs[i], full[sel], rtol=1e-6)

    # spy member: the frame table it sees must be exactly the live frames
    seen = {}

    class Spy:
        def predict_proba(self, X):
            seen["n"] = len(X)
            return np.full((len(X), NUM_CLASSES), 0.25, np.float32)

    com.host_members.append(Spy())
    com.pool_probs(pool, None, live, jax.random.key(0))
    assert seen["n"] == sum(pool.count_of(s) for s in live)
    assert seen["n"] < len(pool.X)
