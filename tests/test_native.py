"""Native C++ host runtime vs sklearn/scipy/pandas oracles, on BOTH backends
(the compiled OpenMP library and the numpy fallback)."""

import importlib
import os
import subprocess
import sys

import numpy as np
import pytest
from scipy.stats import entropy as scipy_entropy
from sklearn.linear_model import SGDClassifier
from sklearn.naive_bayes import GaussianNB

from consensus_entropy_tpu import native


def _fallback_env():
    env = dict(os.environ)
    env["CE_TPU_NO_NATIVE"] = "1"
    return env


def test_native_backend_compiles():
    # This image ships g++; the native backend must actually build here.
    assert native.backend() == "native"
    assert native.num_threads() >= 1


def test_numpy_fallback_importable():
    # Fallback path must import and answer in a clean subprocess.
    code = ("import numpy as np\n"
            "from consensus_entropy_tpu import native\n"
            "assert native.backend() == 'numpy'\n"
            "p = native.linear_predict_proba(np.ones((3, 4), np.float32),"
            " np.ones((4, 2), np.float32), np.zeros(2, np.float32))\n"
            "assert p.shape == (3, 2)\n"
            "print('fallback ok')\n")
    out = subprocess.run([sys.executable, "-c", code], env=_fallback_env(),
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "fallback ok" in out.stdout


@pytest.fixture
def problem(rng):
    X = rng.standard_normal((200, 12)).astype(np.float32)
    y = rng.integers(0, 4, 200)
    return X, y


def test_gnb_parity(problem):
    X, y = problem
    est = GaussianNB().fit(X, y)
    want = est.predict_proba(X)
    got = native.gnb_predict_proba(X, est.theta_, est.var_, est.class_prior_)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
    via_member = native.member_probs(est, X)
    np.testing.assert_array_equal(got, via_member)


def test_sgd_ova_parity(problem):
    X, y = problem
    est = SGDClassifier(loss="log_loss", random_state=0).fit(X, y)
    want = est.predict_proba(X)
    got = native.member_probs(est, X)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_linear_softmax_matches_oracle(rng):
    X = rng.standard_normal((50, 8)).astype(np.float32)
    W = rng.standard_normal((8, 4)).astype(np.float32)
    b = rng.standard_normal(4).astype(np.float32)
    got = native.linear_predict_proba(X, W, b, mode="softmax")
    logits = X.astype(np.float64) @ W.astype(np.float64) + b
    logits -= logits.max(axis=1, keepdims=True)
    want = np.exp(logits)
    want /= want.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-5)


def test_segment_mean_groupby_parity(rng):
    import pandas as pd

    ids = np.sort(rng.integers(0, 30, 500))
    X = rng.standard_normal((500, 4)).astype(np.float32)
    starts = native.segment_starts(ids)
    got = native.segment_mean(X, starts)
    want = pd.DataFrame(X).groupby(ids).mean().to_numpy()
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_row_entropy_scipy_parity(rng):
    P = rng.uniform(0.0, 1.0, (100, 4)).astype(np.float32)
    P[0] = [1, 0, 0, 0]          # zero-probability classes
    P[1] = [0.25, 0.25, 0.25, 0.25]
    got = native.row_entropy(P)
    want = scipy_entropy(P.astype(np.float64), axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_fallback_matches_native(problem, rng, monkeypatch):
    # Force the numpy implementations in-process and compare against the
    # native ones on identical inputs.
    X, y = problem
    est = GaussianNB().fit(X, y)
    native_gnb = native.gnb_predict_proba(X, est.theta_, est.var_,
                                          est.class_prior_)
    P = rng.uniform(0.01, 1.0, (64, 4)).astype(np.float32)
    native_ent = native.row_entropy(P)
    W = rng.standard_normal((12, 4)).astype(np.float32)
    b = np.zeros(4, np.float32)
    native_lin = native.linear_predict_proba(X, W, b, mode="ova")

    monkeypatch.setattr(native, "_lib", None)
    assert native.backend() == "numpy"
    np.testing.assert_allclose(
        native.gnb_predict_proba(X, est.theta_, est.var_, est.class_prior_),
        native_gnb, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(native.row_entropy(P), native_ent,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(native.linear_predict_proba(X, W, b, "ova"),
                               native_lin, rtol=1e-5, atol=1e-6)


def test_member_predict_parity(problem):
    # The evaluation hot path (al/loop.py _evaluate via Member.predict)
    # must agree with sklearn's own predict on both native species.
    X, y = problem
    gnb = GaussianNB().fit(X, y)
    sgd = SGDClassifier(loss="log_loss", random_state=0).fit(X, y)
    for est in (gnb, sgd):
        got = native.member_predict(est, X)
        assert got is not None
        np.testing.assert_array_equal(got, est.predict(X))


def test_member_predict_subset_classes(problem):
    # classes_ mapping: a member fitted on 2 of the 4 classes must return
    # the ORIGINAL labels, not argmax slots.
    X, y = problem
    keep = np.isin(y, (1, 3))
    gnb = GaussianNB().fit(X[keep], y[keep])
    got = native.member_predict(gnb, X)
    assert set(np.unique(got)) <= {1, 3}
    np.testing.assert_array_equal(got, gnb.predict(X))


def test_member_predict_declines_without_fast_path(problem):
    from sklearn.tree import DecisionTreeClassifier

    X, y = problem
    assert native.member_predict(
        DecisionTreeClassifier(max_depth=2).fit(X, y), X) is None


def test_ova_sigmoid_saturates_without_overflow(problem):
    # Saturated logits (|x| >> 88) used to overflow float32 exp in the
    # numpy OvA path (63 RuntimeWarnings across the round-3 suite); the
    # clipped sigmoid must stay warning-free and return exact 0/1 rows.
    import warnings

    X, y = problem
    est = SGDClassifier(loss="log_loss", random_state=0).fit(X, y)
    est.coef_ = est.coef_ * 1e4       # drive |logits| into the thousands
    est.intercept_ = est.intercept_ * 1e4
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        p = native.member_probs(est, X)
        lp = native.linear_predict_proba(
            X * 1e3, est.coef_.T.astype(np.float32),
            est.intercept_.astype(np.float32), mode="ova")
    for out in (p, lp):
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)


def test_ova_saturated_rows_keep_relative_magnitudes():
    # An all-rejecting row with DISTINCT magnitudes must normalize to the
    # least-rejected class, not collapse to uniform (a naive clip would):
    # the stable sigmoid preserves exp-scale ratios down to underflow,
    # matching the C++ core's double-precision behavior within float32.
    from consensus_entropy_tpu.native import _ova_normalize, _sigmoid

    import warnings

    row = np.array([[-61.0, -100.0, -200.0, -300.0]], np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        p = _ova_normalize(_sigmoid(row))
    np.testing.assert_allclose(p, [[1.0, 0.0, 0.0, 0.0]], atol=1e-12)


def test_segment_starts_validation():
    with pytest.raises(ValueError):
        native.segment_mean(np.ones((4, 2), np.float32),
                            np.array([1, 4], np.int64))
    assert native.segment_starts(np.array([])).tolist() == [0]


def test_race_check_script(tmp_path):
    """The sanitizer sweep (scripts/race_check.sh): TSAN reentrancy over
    concurrent kernel callers + bytewise determinism under oversubscribed
    OpenMP.  Skipped where the toolchain lacks libtsan; measured ~5 s
    total (two small compiles + short stress runs), cheap enough to live
    in the default suite rather than rot behind an opt-in flag."""
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    probe = subprocess.run(
        ["g++", "-fsanitize=thread", "-fopenmp", "-x", "c++", "-", "-o",
         str(tmp_path / "probe")],
        input="int main(){return 0;}", text=True, capture_output=True)
    if probe.returncode != 0:
        pytest.skip("toolchain lacks ThreadSanitizer support")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "race_check.sh")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "TMPDIR": str(tmp_path)})
    assert res.returncode == 0, res.stdout + res.stderr
    assert "race check passed" in res.stdout
