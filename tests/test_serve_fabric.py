"""Multi-host serve fabric: journal-coordinated sharding + lease failover.

The headline drill runs a REAL 2-host fabric (worker subprocesses over
the synthetic ``tests/fabric_workload`` users), SIGKILLs one worker
mid-iteration, and asserts the coordinator recovers EVERY user — finished
skipped, in-flight resumed on the survivor from their durable
workspaces, queued re-enqueued in journal order — with per-user
trajectories bit-identical to uninterrupted single-host runs.  Tier-1
keeps the pure-host units (fabric journal records, compaction incl. the
kill-between-renames window, torn-tail repair, unpoison, breaker probe
budget, lease heartbeat) plus ONE 2-host mc kill case (the acceptance
pin); the 4-mode matrix, the coordinator-SIGKILL restart and the
lease-expiry hang drill are ``slow`` and run via
``scripts/fault_matrix.sh``.
"""

import os
import subprocess
import sys
import time

import pytest

from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    DispatchBreaker,
    FabricConfig,
    FabricCoordinator,
    HostLease,
    JournalState,
    JsonlTail,
    PoisonList,
)
from consensus_entropy_tpu.serve.hosts import (
    fabric_paths,
    lease_age_s,
    read_lease,
)
from tests.fabric_workload import (
    make_cfg,
    read_results,
    sequential_baselines,
    user_specs,
)

pytestmark = [pytest.mark.serve, pytest.mark.faults]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fabric_worker.py")


# -- pure-host units (no subprocesses) -------------------------------------


def test_journal_fabric_records_and_roundtrip(tmp_path):
    """assign/lease/revoke ride the journal without touching admission
    dispositions; the state checkpoint round-trips losslessly."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        for u in ("a", "b", "c"):
            j.append("enqueue", u)
        j.append("lease", host="h0", pid=1)
        j.append("lease", host="h1", pid=2)
        j.append("assign", "a", host="h0")
        j.append("assign", "b", host="h1")
        j.append("assign", "c", host="h0")
        j.append("admit", "a", host="h0", src_off=64)
        j.append("revoke", host="h0", reason="drill")
        j.append("assign", "a", host="h1")
        j.append("assign", "c", host="h1")
    st = AdmissionJournal(jp).state
    assert st.hosts == {"h0": "revoke", "h1": "lease"}
    assert st.live_hosts() == ["h1"]
    assert st.assigned == {"a": "h1", "b": "h1", "c": "h1"}
    assert st.host_cursor == {"h0": 64}
    # assign never changed dispositions: a in-flight, b/c still queued
    assert st.in_flight == ["a"] and st.queued == ["b", "c"]
    # failover order: in-flight first, then queued in enqueue order
    assert st.assigned_to("h1") == ["a", "b", "c"]
    rt = JournalState.from_dict(st.to_dict())
    assert rt.to_dict() == st.to_dict()
    with pytest.raises(ValueError, match="needs host"):
        AdmissionJournal(None).append("lease")
    with pytest.raises(ValueError, match="needs a user"):
        AdmissionJournal(None).append("enqueue")


def test_journal_compaction_bounds_size_across_cycles(tmp_path):
    """≥3 checkpoint-truncate cycles keep the WAL below its bound while
    the replayed state stays complete — order included."""
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp, compact_bytes=600)
    for i in range(200):
        j.append("enqueue", f"user_{i:04d}")
    j.append("admit", "user_0000")
    assert j.compactions >= 3
    assert os.path.getsize(jp) <= 600 + 200  # bound + one-record overshoot
    assert os.path.exists(j.ckpt_path)
    j.close()
    st = AdmissionJournal(jp).state
    assert st.in_flight == ["user_0000"]
    assert len(st.queued) == 199
    assert st.queued[:2] == ["user_0001", "user_0002"]  # order preserved


def test_journal_compaction_kill_windows_recover_losslessly(tmp_path):
    """A kill in EITHER compaction window — before the checkpoint rename,
    or between it and the journal truncation — replays to the identical
    state (seq-deduped), and the next compaction completes normally."""
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp)
    for i in range(6):
        j.append("enqueue", f"u{i}")
    j.append("admit", "u0")
    j.append("finish", "u0")
    expect = j.state.to_dict()
    # window 2: ckpt renamed, journal NOT truncated (stale tail on disk)
    with faults.inject(FaultRule("fabric.compact", "kill", at=2)) as inj:
        with pytest.raises(InjectedKill):
            j.compact()
        assert inj.fired
    j.close()
    assert os.path.exists(jp + ".ckpt") and os.path.getsize(jp) > 0
    j2 = AdmissionJournal(jp)
    assert j2.state.to_dict() == expect  # stale records deduped by seq
    # window 1: before the checkpoint write — nothing changed
    with faults.inject(FaultRule("fabric.compact", "kill", at=1)):
        with pytest.raises(InjectedKill):
            j2.compact()
    j2.close()
    j3 = AdmissionJournal(jp)
    assert j3.state.to_dict() == expect
    j3.compact()  # a clean compaction still works after both crashes
    # the truncated journal holds exactly the CRC frame header (ISSUE 19)
    from consensus_entropy_tpu.resilience import io as dio
    assert open(jp, "rb").read() == dio.frame_header()
    j3.append("enqueue", "zz")
    j3.close()
    st = AdmissionJournal(jp).state
    assert st.to_dict()["last"]["zz"] == "enqueue"
    assert st.finished == {"u0"} and len(st.queued) == 6


def test_journal_ckpt_skips_legacy_seqless_lines(tmp_path):
    """A crash between compaction's two renames over a journal that
    still holds PRE-SEQ (legacy-writer) lines must not re-apply those
    lines on top of the checkpoint: they predate it by construction, and
    replaying them would regress dispositions (a finished user back to
    admitted) and double-count the failure budget."""
    import json as _json

    jp = str(tmp_path / "j.jsonl")
    # a legacy journal: no seq fields (the committed pre-compaction code)
    with open(jp, "wb") as f:
        for ev in ({"event": "enqueue", "user": "a"},
                   {"event": "admit", "user": "a"}):
            f.write((_json.dumps(ev) + "\n").encode())
    j = AdmissionJournal(jp)
    assert j.state.in_flight == ["a"] and j.state.admits == {"a": 1}
    j.append("finish", "a")  # new writer: seq'd record
    # crash between the checkpoint rename and the journal truncation:
    # the new ckpt coexists with the FULL stale journal (legacy lines
    # included)
    with faults.inject(FaultRule("fabric.compact", "kill", at=2)):
        with pytest.raises(InjectedKill):
            j.compact()
    j.close()
    st = AdmissionJournal(jp).state
    assert st.finished == {"a"} and not st.pending  # finish NOT regressed
    assert st.admits == {"a": 1}  # budget not double-counted


def test_journal_single_writer_lock(tmp_path):
    """The append-fsync WAL is single-writer by ENFORCEMENT: a second
    live writer (the --unpoison-vs-running-server hazard) raises instead
    of interleaving seq numbers; read-only replays never take the lock,
    and close releases it."""
    from consensus_entropy_tpu.serve import SingleWriterViolation

    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp)
    j.append("enqueue", "a")
    second = AdmissionJournal(jp)  # replay-only: allowed
    assert second.state.queued == ["a"]
    with pytest.raises(SingleWriterViolation):
        second.append("enqueue", "b")
    # compaction rotates the data handle but KEEPS the lock
    j.compact()
    with pytest.raises(SingleWriterViolation):
        second.append("enqueue", "b")
    j.close()
    second.append("enqueue", "b")  # lock released: new writer may own it
    second.close()
    st = AdmissionJournal(jp).state
    assert st.queued == ["a", "b"]


def test_journal_torn_tail_repair_preserves_next_append(tmp_path):
    """A journal whose last line is torn (died mid-append) must not
    swallow the first post-restart append into the torn line."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        j.append("enqueue", "a")
    with open(jp, "ab") as f:
        f.write(b'{"event": "enq')  # the crash artifact
    with AdmissionJournal(jp) as j2:
        j2.append("enqueue", "b")
    st = AdmissionJournal(jp).state
    assert st.queued == ["a", "b"]  # b survived the torn neighbour


def test_poison_list_torn_tail_repair(tmp_path):
    """The poison list replays across a torn tail line exactly like the
    main journal does, and a post-restart add is NOT merged into (and
    lost with) the torn line."""
    pp = str(tmp_path / "p.jsonl")
    p = PoisonList(pp)
    p.add("a", error="e1", attempts=2)
    p.add("b", error="e2", attempts=3)
    p.close()
    with open(pp, "ab") as f:
        f.write(b'{"user": "c", "err')  # torn mid-append
    p2 = PoisonList(pp)
    assert "a" in p2 and "b" in p2 and "c" not in p2
    p2.add("d", error="e3", attempts=1)
    p2.close()
    p3 = PoisonList(pp)
    assert "d" in p3 and "a" in p3 and "b" in p3 and len(p3) == 3


def test_unpoison_resets_user_and_budget(tmp_path):
    """An ``unpoison`` record clears the poisoned disposition AND the
    replayed failure-budget counters, making the user submittable again
    in its given order."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        j.append("enqueue", "x")
        j.append("admit", "x")
        j.append("fail", "x", error="e")
        j.append("poison", "x", error="e", attempts=3)
    st = AdmissionJournal(jp).state
    assert st.poisoned == {"x"}
    assert st.recovery_order(["x", "y"]) == ["y"]  # poisoned dropped
    with AdmissionJournal(jp) as j:
        j.append("unpoison", "x")
    st = AdmissionJournal(jp).state
    assert st.poisoned == set()
    assert st.admits == {} and st.fails == {}  # fresh budget
    assert st.recovery_order(["x", "y"]) == ["x", "y"]


def test_unpoison_cli_roundtrip(tmp_path, capsys):
    """``--unpoison`` removes via journaled records (poison file AND the
    admission journal) and exits nonzero for unknown users."""
    from consensus_entropy_tpu.cli.amg_test import main

    users_dir = tmp_path / "users"
    users_dir.mkdir()
    p = PoisonList(str(users_dir / "serve_poison.jsonl"))
    p.add("u7", error="boom", attempts=3)
    p.close()
    with AdmissionJournal(str(users_dir / "serve_journal.jsonl")) as j:
        j.append("poison", "u7", error="boom", attempts=3)
    base = ["-q", "1", "-e", "1", "-n", "1", "-m", "mc",
            "--models-root", str(tmp_path)]
    assert main(base + ["--unpoison", "u7"]) == 0
    assert "unpoisoned user u7" in capsys.readouterr().out
    assert "u7" not in PoisonList(str(users_dir / "serve_poison.jsonl"))
    st = AdmissionJournal(str(users_dir / "serve_journal.jsonl")).state
    assert st.poisoned == set() and st.last["u7"] == "unpoison"
    assert main(base + ["--unpoison", "u7"]) == 1  # no longer on the list


def test_breaker_probe_budget_gives_width_up():
    """After ``probe_budget`` failed half-open probes the width stays
    per-user for the run (no more probes) and the giveup lands in the
    telemetry events + summary."""
    clock = [0.0]
    breaker = DispatchBreaker(1, 1.0, probe_budget=1,
                              clock=lambda: clock[0])
    report = FleetReport()
    sched = FleetScheduler(make_cfg("mc"), report=report, breaker=breaker)
    sched._note_stacked_failure("mc", 32, RuntimeError("boom"))
    assert breaker.state_of(32) == "open"
    clock[0] = 2.0
    assert breaker.allow_stacked(32)  # the half-open probe
    sched._note_stacked_failure("mc", 32, RuntimeError("boom"))
    assert breaker.state_of(32) == "gave_up"
    clock[0] = 100.0
    assert not breaker.allow_stacked(32)  # no probes ever again
    assert breaker.allow_stacked(64)  # other widths unaffected
    assert breaker.summary() == {32: "gave_up"}
    evs = [e["event"] for e in report.events]
    assert "breaker_open" in evs and "breaker_giveup" in evs
    s = report.summary(cohort=2)
    assert s["breaker_giveups"] == 1
    with pytest.raises(ValueError):
        DispatchBreaker(1, 1.0, probe_budget=-1)


def test_host_lease_beat_read_age_and_fault_point(tmp_path):
    lp = str(tmp_path / "lease.json")
    lease = HostLease(lp, "h0", 0.1)
    lease.beat_once()
    rec = read_lease(lp)
    assert rec["host"] == "h0" and rec["pid"] == os.getpid()
    assert rec["beat"] == 1
    assert 0 <= lease_age_s(lp) < 5.0
    # the fault point fires BEFORE the write: a killed beat leaves the
    # previous lease on disk, which then goes stale (the failover
    # signal).  at=1: hit counters are injector-local, so the first beat
    # under this injector is hit 1 regardless of earlier beats.
    with faults.inject(FaultRule("fabric.lease", "kill", at=1)) as inj:
        with pytest.raises(InjectedKill):
            lease.beat_once()
        assert inj.fired
    assert read_lease(lp)["beat"] == 1
    assert read_lease(str(tmp_path / "missing.json")) is None
    assert lease_age_s(str(tmp_path / "missing.json")) is None
    with pytest.raises(ValueError):
        HostLease(lp, "h0", 0)


def test_jsonl_tail_partial_lines_and_seek(tmp_path):
    tp = str(tmp_path / "t.jsonl")
    t = JsonlTail(tp)
    assert t.poll() == []  # not yet created
    with open(tp, "wb") as f:
        f.write(b'{"a": 1}\n{"b": 2}\nnot json\n{"c":')
    assert [r for r, _ in t.poll()] == [{"a": 1}, {"b": 2}]
    assert t.poll() == []  # the half line stays unconsumed
    with open(tp, "ab") as f:
        f.write(b' 3}\n')
    polled = t.poll()
    assert [r for r, _ in polled] == [{"c": 3}]
    off = polled[-1][1]
    t2 = JsonlTail(tp)
    t2.seek(off)
    assert t2.poll() == []  # cursor resume: nothing new past off
    t.close()
    t2.close()


# -- the 2-host kill drill -------------------------------------------------


def _spawn_factory(fabric_dir, ws_root, cfg, n_users, *, lease_s=5.0,
                   target=2, env_extra=None):
    def spawn(host_id):
        log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
        env = {**os.environ, "PYTHONPATH": REPO}
        env.pop("CETPU_FAULTS", None)  # in-process rules stay in-process
        env.update((env_extra or {}).get(host_id, {}))
        try:
            return subprocess.Popen(
                [sys.executable, WORKER, fabric_dir, host_id, ws_root,
                 cfg.mode, str(cfg.epochs), str(n_users), str(lease_s),
                 str(target)],
                stdout=log, stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()
    return spawn


def _with_deadline(inner=None, deadline_s=300.0):
    """on_poll hook: optional chaos + a hard drill deadline so a wedged
    fabric fails the test (killing its workers) instead of eating the
    whole tier-1 budget."""
    t0 = time.monotonic()

    def hook(coord):
        if time.monotonic() - t0 > deadline_s:
            raise AssertionError(
                f"fabric drill exceeded {deadline_s}s; journal state: "
                f"unresolved={sorted(coord._unresolved)}")
        if inner is not None:
            inner(coord)
    return hook


def _kill_on_first_admit(host_id="h0"):
    """SIGKILL ``host_id`` the moment the journal shows it admitted a
    user — i.e. mid-iteration, with in-flight AND queued users on the
    host — driven by journal state, not wall clock."""
    state = {"done": False}

    def chaos(coord):
        if state["done"]:
            return
        st = coord.journal.state
        if any(h == host_id and st.last.get(u) == "admit"
               for u, h in st.assigned.items()):
            coord.hosts[host_id].proc.kill()
            state["done"] = True
    return chaos


def _fabric_kill_drill(tmp_path, mode, *, n_users=3, epochs=2,
                       compact_bytes=800, victim="h0"):
    """Run the 2-host fabric over ``n_users``, SIGKILL ``victim`` after
    its first admission, assert total recovery + bit-identical parity."""
    cfg = make_cfg(mode, epochs=epochs)
    specs = user_specs(n_users)
    seq = sequential_baselines(str(tmp_path), cfg, specs)
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    journal = AdmissionJournal(jp, compact_bytes=compact_bytes)
    report = FleetReport()
    coord = FabricCoordinator(
        journal, fabric_dir, FabricConfig(hosts=2, lease_s=5.0),
        report=report,
        on_poll=_with_deadline(_kill_on_first_admit(victim)))
    try:
        summary = coord.run([u for _, u, _ in specs],
                            _spawn_factory(fabric_dir, str(tmp_path), cfg,
                                           n_users))
    finally:
        journal.close()
    assert sorted(summary["finished"]) == [u for _, u, _ in specs]
    assert summary["failed"] == [] and summary["poisoned"] == []
    assert summary["revocations"] == 1
    assert summary["reassignments"] >= 1  # the victim's users moved over
    assert summary["hosts"][victim] == "revoked"
    results = read_results(fabric_dir)
    for _, uid, _ in specs:
        assert results[uid]["error"] is None
        assert results[uid]["result"]["trajectory"] \
            == seq[uid]["trajectory"]
        assert results[uid]["result"]["final_mean_f1"] \
            == seq[uid]["final_mean_f1"]
    # the journal is the record: replay shows everyone finished, the dead
    # host revoked, and compaction kept the WAL bounded
    st = AdmissionJournal(jp).state
    assert st.finished == {u for _, u, _ in specs}
    assert not st.pending
    survivor = "h1" if victim == "h0" else "h0"
    assert st.hosts[victim] == "revoke" and st.hosts[survivor] == "lease"
    assert os.path.getsize(jp) <= compact_bytes + 300
    return summary, report


def test_fabric_two_hosts_worker_sigkill_recovers_all_users(tmp_path):
    """THE acceptance pin (tier-1 case): a 2-host mc fabric with one
    worker SIGKILLed mid-iteration recovers every user — in-flight
    resumed on the survivor, queued re-enqueued in journal order — with
    per-user trajectories bit-identical to uninterrupted single-host
    runs, while journal compaction keeps the WAL bounded."""
    summary, report = _fabric_kill_drill(tmp_path, "mc")
    evs = [e["event"] for e in report.events]
    assert "host_down" in evs and "assign" in evs
    down = next(e for e in report.events if e["event"] == "host_down")
    assert down["host"] == "h0" and down["reassigned"] >= 1
    assert summary["compactions"] >= 1


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["hc", "mix", "rand", "wmc", "qbdc"])
def test_fabric_kill_matrix_all_modes(tmp_path, mode):
    """Acceptance: the same worker-SIGKILL recovery is bit-identical in
    every acquisition mode (mc is the tier-1 case above) — including the
    registry extensions: wmc's reliability weights ride ALState through
    the failover resume, and qbdc's dropout-mask keys fold from the
    checkpointed PRNG stream, so the re-routed users' committees are the
    SAME committees on the surviving host."""
    _fabric_kill_drill(tmp_path, mode)


@pytest.mark.slow
def test_fabric_kill_matrix_other_worker(tmp_path):
    """The kill matrix covers EACH worker: losing h1 (the other shard)
    recovers identically — failover is symmetric, not h0-special."""
    _fabric_kill_drill(tmp_path, "mc", victim="h1")


@pytest.mark.slow
def test_fabric_lease_expiry_hang_fails_over(tmp_path):
    """A worker whose heartbeat thread dies (injected kill at its 2nd
    beat via CETPU_FAULTS — the engine itself keeps running, the classic
    wedged-host shape) is SIGKILLed on lease expiry and its users fail
    over; every user still finishes with sequential-identical results."""
    cfg = make_cfg("mc", epochs=3)
    specs = user_specs(4)
    seq = sequential_baselines(str(tmp_path), cfg, specs)
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    journal = AdmissionJournal(jp)
    report = FleetReport()
    coord = FabricCoordinator(
        journal, fabric_dir, FabricConfig(hosts=2, lease_s=1.5),
        report=report, on_poll=_with_deadline())
    spawn = _spawn_factory(
        fabric_dir, str(tmp_path), cfg, 4, lease_s=1.5,
        env_extra={"h0": {"CETPU_FAULTS": "fabric.lease:kill@2"}})
    try:
        summary = coord.run([u for _, u, _ in specs], spawn)
    finally:
        journal.close()
    assert summary["revocations"] == 1
    down = next(e for e in report.events if e["event"] == "host_down")
    assert down["host"] == "h0" and "lease expired" in down["reason"]
    assert sorted(summary["finished"]) == [u for _, u, _ in specs]
    results = read_results(fabric_dir)
    for _, uid, _ in specs:
        assert results[uid]["error"] is None
        assert results[uid]["result"]["trajectory"] \
            == seq[uid]["trajectory"]


COORD_SCRIPT = '''\
import os, subprocess, sys
repo = {repo!r}
sys.path.insert(0, repo)
fabric_dir, ws_root, mode, epochs, n_users, lease_s = sys.argv[1:7]
from tests.fabric_workload import configure_jax, user_specs
configure_jax()
from consensus_entropy_tpu.serve import (
    AdmissionJournal, FabricConfig, FabricCoordinator)
from consensus_entropy_tpu.serve.hosts import fabric_paths
worker = os.path.join(repo, "tests", "fabric_worker.py")

def spawn(host_id):
    log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
    try:
        return subprocess.Popen(
            [sys.executable, worker, fabric_dir, host_id, ws_root, mode,
             epochs, n_users, lease_s, "2"],
            stdout=log, stderr=subprocess.STDOUT,
            env={{**os.environ, "PYTHONPATH": repo}})
    finally:
        log.close()

journal = AdmissionJournal(
    os.path.join(fabric_dir, "serve_journal.jsonl"), compact_bytes=8192)
coord = FabricCoordinator(journal, fabric_dir,
                          FabricConfig(hosts=2, lease_s=float(lease_s)))
summary = coord.run([u for _, u, _ in user_specs(int(n_users))], spawn)
journal.close()
print("COORD_DONE", len(summary["finished"]), flush=True)
'''


@pytest.mark.slow
def test_fabric_coordinator_sigkill_restart_recovers(tmp_path):
    """SIGKILL the COORDINATOR mid-run: its workers orphan-exit (ppid
    watch in the lease thread), and a rerun replays the journal — reaping
    any straggler via the lease pid, skipping finished users, re-routing
    the rest — to a complete, bit-identical fabric."""
    cfg = make_cfg("mc", epochs=2)
    specs = user_specs(3)
    seq = sequential_baselines(str(tmp_path), cfg, specs)
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    script = tmp_path / "coord.py"
    script.write_text(COORD_SCRIPT.format(repo=REPO))
    argv = [sys.executable, str(script), fabric_dir, str(tmp_path), "mc",
            "2", "3", "2.0"]
    env = {**os.environ, "PYTHONPATH": REPO}
    env.pop("CETPU_FAULTS", None)
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    clog = open(str(tmp_path / "coord1.log"), "ab")
    p1 = subprocess.Popen(argv, stdout=clog, stderr=subprocess.STDOUT,
                          env=env)
    clog.close()
    try:
        deadline = time.monotonic() + 300
        killed = False
        while time.monotonic() < deadline:
            if p1.poll() is not None:
                break  # finished before we could kill (degenerate; rare)
            if os.path.exists(jp) \
                    and b'"event": "admit"' in open(jp, "rb").read():
                p1.kill()  # SIGKILL mid-run, with users in flight
                killed = True
                break
            time.sleep(0.1)
        p1.wait(timeout=30)
        assert killed or p1.returncode == 0
    finally:
        if p1.poll() is None:
            p1.kill()
            p1.wait()
    # give the orphaned workers one heartbeat interval to self-exit; the
    # rerun's lease-pid reaper covers any straggler
    time.sleep(2.5)
    out = subprocess.run(argv, capture_output=True, text=True, env=env,
                         timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "COORD_DONE 3" in out.stdout
    results = read_results(fabric_dir)
    for _, uid, _ in specs:
        assert results[uid]["error"] is None
        assert results[uid]["result"]["trajectory"] \
            == seq[uid]["trajectory"]
    st = AdmissionJournal(jp).state
    assert st.finished == {u for _, u, _ in specs} and not st.pending
