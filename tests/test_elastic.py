"""Elastic fabric control plane: autoscaler, JOIN/rebalance,
bucket-aware placement, fabric-level planner (``serve.elastic`` +
``serve.placement``).

Tier-1 keeps the pure-host decision kernels (placement, rebalance
planning, autoscaler sizing, host-id allocation, journal fleet-shape
replay, the drop-record semantics, the journal validator, the
batch-reserve queue and the telemetry-sized dispatch hold), the
DETERMINISTIC fake-worker drills (the coordinator drives real feeds /
leases / event WALs while the test plays the workers — join, rebalance
drop-ack, fleet-edge broadcast and the coordinator-kill-mid-rebalance
replay are all journal-state-scripted, no subprocess timing), and ONE
real-subprocess acceptance drill: a 2-host elastic fabric with a worker
SIGKILLed mid-run must end with the autoscaler having respawned a
replacement and every user bit-identical to uninterrupted sequential
runs.  The mode matrix and the operator-adoption subprocess drill are
``slow`` (``scripts/fault_matrix.sh`` / ``scripts/elastic_check.sh``).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from consensus_entropy_tpu.obs.metrics import QuantileSketch
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    AdmissionPlanner,
    AdmissionQueue,
    BucketRouter,
    FabricConfig,
    FabricCoordinator,
    FleetPlanner,
    JournalState,
    JsonlTail,
    ServeConfig,
    bucket_for,
    derive_edges,
    dispatch_hold,
    drain_victim,
    next_host_id,
    place,
    place_user,
    plan_failover,
    plan_rebalance,
    scale_down_ok,
    target_hosts,
    validate_journal_file,
)
from consensus_entropy_tpu.serve.hosts import fabric_paths
from tests.fabric_workload import (
    force_low_water,
    make_cfg,
    read_results,
    sequential_baselines,
    sizes_arg,
    user_specs,
)

pytestmark = [pytest.mark.serve, pytest.mark.faults]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fabric_worker.py")


# -- config validation (the bugfix satellite) ------------------------------


def test_fabric_config_elastic_validation():
    """Elastic knobs validate at CONSTRUCTION with the reason — the
    validate_bucket_widths precedent — and one bound defaults the
    other."""
    c = FabricConfig(hosts=2, min_hosts=2, max_hosts=4)
    assert c.elastic and (c.min_hosts, c.max_hosts) == (2, 4)
    c = FabricConfig(hosts=3, min_hosts=2)  # max defaults to hosts
    assert (c.min_hosts, c.max_hosts) == (2, 3)
    c = FabricConfig(hosts=2, max_hosts=5)  # min defaults to hosts
    assert (c.min_hosts, c.max_hosts) == (2, 5)
    assert not FabricConfig(hosts=2).elastic  # PR 5 shape: all off
    with pytest.raises(ValueError, match="min_hosts must be <= max_hosts"):
        FabricConfig(hosts=3, min_hosts=4, max_hosts=3)
    with pytest.raises(ValueError, match="inside"):
        FabricConfig(hosts=5, min_hosts=1, max_hosts=4)
    with pytest.raises(ValueError, match="min_hosts"):
        FabricConfig(hosts=1, min_hosts=0, max_hosts=1)
    with pytest.raises(ValueError, match="scale_backlog"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, scale_backlog=0)
    with pytest.raises(ValueError, match="scale_slo_s"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, scale_slo_s=-1)
    with pytest.raises(ValueError, match="placement"):
        FabricConfig(hosts=2, placement="random")
    with pytest.raises(ValueError, match="hosts must be >= 1"):
        FabricConfig(hosts=0)
    with pytest.raises(ValueError, match="lease_s"):
        FabricConfig(hosts=2, lease_s=0)
    # the journal's compaction bound validates at construction too
    with pytest.raises(ValueError, match="compact_bytes"):
        AdmissionJournal(None, compact_bytes=0)
    with pytest.raises(ValueError, match="compact_bytes"):
        AdmissionJournal(None, compact_bytes=-4)
    # scale-down knobs: elastic-only, non-negative
    c = FabricConfig(hosts=3, min_hosts=2, max_hosts=3, scale_down_s=5.0)
    assert c.scale_down_s == 5.0 and c.migrate_inflight
    with pytest.raises(ValueError, match="scale_down_s"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, scale_down_s=-1)
    with pytest.raises(ValueError, match="elastic"):
        FabricConfig(hosts=2, scale_down_s=5.0)


def test_elastic_cli_flag_validation(tmp_path):
    """Clean CLI errors for typo'd elastic geometry, before any data or
    backend work."""
    from consensus_entropy_tpu.cli.amg_test import main

    base = ["-q", "1", "-e", "1", "-n", "1", "-m", "mc",
            "--models-root", str(tmp_path)]
    assert main(base + ["--min-hosts", "2"]) == 1  # needs --serve
    assert main(base + ["--serve", "1", "--min-hosts", "2"]) == 1  # --hosts
    assert main(base + ["--serve", "1", "--hosts", "2",
                        "--min-hosts", "3", "--max-hosts", "2"]) == 1
    assert main(base + ["--serve", "1", "--hosts", "5",
                        "--min-hosts", "1", "--max-hosts", "4"]) == 1
    # scale-down needs the elastic gate (and --hosts before that)
    assert main(base + ["--serve", "1", "--scale-down-s", "5"]) == 1
    assert main(base + ["--serve", "1", "--hosts", "2",
                        "--scale-down-s", "-1", "--min-hosts", "2"]) == 1


# -- autoscaler decision kernels (pure host) -------------------------------


def test_next_host_id_never_reuses():
    assert next_host_id([]) == "h0"
    assert next_host_id(["h0", "h1"]) == "h2"
    # revoked ids stay burned: their event WAL + cursor belong to the
    # dead process
    assert next_host_id(["h0", "h2"]) == "h3"
    assert next_host_id(["h0", "weird", "h10"]) == "h11"


def test_target_hosts_decision_table():
    kw = dict(min_hosts=2, max_hosts=4, scale_backlog=4)
    # dead capacity below the floor is replaced
    assert target_hosts(live=0, queued=1, **kw) == 2
    assert target_hosts(live=1, queued=0, **kw) == 2
    # healthy fleet, light queue: hold
    assert target_hosts(live=2, queued=8, **kw) == 2
    # queue-depth signal: backlog per live host exceeded -> +1
    assert target_hosts(live=2, queued=9, **kw) == 3
    assert target_hosts(live=3, queued=13, **kw) == 4
    # ceiling holds no matter the backlog
    assert target_hosts(live=4, queued=1000, **kw) == 4
    # SLO-headroom signal: predicted drain time past the target -> +1
    assert target_hosts(live=2, queued=5, scale_slo_s=10.0,
                        finish_ema_s=3.0, **kw) == 3
    assert target_hosts(live=2, queued=5, scale_slo_s=60.0,
                        finish_ema_s=3.0, **kw) == 2
    # no finish telemetry yet -> unpredictable -> no SLO scale-up
    assert target_hosts(live=2, queued=5, scale_slo_s=10.0,
                        finish_ema_s=None, **kw) == 2


def test_scale_down_ok_decision_table():
    """The low-water kernel: both scale-up signals must be quiet AT THE
    POST-DRAIN SIZE — the exact inverse of target_hosts' triggers, so
    drain and spawn can never flap at the boundary."""
    kw = dict(min_hosts=1, scale_backlog=4)
    # the floor holds, and a 1-host fleet can never shrink
    assert not scale_down_ok(live=1, queued=0, **kw)
    assert not scale_down_ok(live=2, queued=0, min_hosts=2)
    # queue-depth quiet at live-1: ok; one past it: not
    assert scale_down_ok(live=3, queued=8, **kw)
    assert not scale_down_ok(live=3, queued=9, **kw)
    # the boundary is flap-free: any state that allows a drain would
    # NOT immediately re-trigger the scale-up signal at live-1
    for queued in range(0, 20):
        if scale_down_ok(live=3, queued=queued, **kw):
            assert target_hosts(live=2, queued=queued, min_hosts=1,
                                max_hosts=4, scale_backlog=4) == 2
    # SLO-headroom quiet at live-1 (drain time scales by live/(live-1))
    slo = dict(min_hosts=1, scale_backlog=100, scale_slo_s=10.0)
    assert scale_down_ok(live=2, queued=2, finish_ema_s=2.0, **slo)
    assert not scale_down_ok(live=2, queued=4, finish_ema_s=2.0, **slo)
    # no finish telemetry: the SLO term is unpredictable -> permissive
    # (the queue-depth term still gates)
    assert scale_down_ok(live=2, queued=2, finish_ema_s=None, **slo)


def test_drain_victim_choice():
    # fewest unresolved users first (least sunk work to shed)
    assert drain_victim({"h0": 3, "h1": 1, "h2": 2}) == "h1"
    # ties: the NEWEST (highest-numbered) host drains first, walking
    # the fleet back toward its original ids
    assert drain_victim({"h0": 1, "h2": 1}) == "h2"
    assert drain_victim({"h0": 0, "h1": 0, "h10": 0}) == "h10"
    # operator-named volunteers drain ahead of numbered capacity
    assert drain_victim({"h0": 1, "vol": 1}) == "vol"
    with pytest.raises(ValueError, match="drainable"):
        drain_victim({})


# -- placement kernels (pure host) -----------------------------------------


def test_bucket_for_edges_and_pow2():
    assert bucket_for(None) is None
    assert bucket_for(30) == 32 and bucket_for(100) == 128  # pow2 default
    assert bucket_for(100, (120, 480)) == 120
    assert bucket_for(480, (120, 480)) == 480
    assert bucket_for(481, (120, 480)) == 512  # total: pow2 fall-through
    # agreement with the router every worker actually pads by
    r = BucketRouter()
    r.update((120, 480))
    for n in (1, 100, 120, 200, 481):
        assert bucket_for(n, (120, 480)) == r.width_for(n)


def test_place_colocates_buckets_within_skew():
    loads = {"h0": 2, "h1": 2}
    buckets = {"h0": {32: 2}, "h1": {128: 2}}
    # same-bucket users co-locate: a 32-bucket user joins h0, a
    # 128-bucket user joins h1 — stacked dispatches stay full per host
    assert place(32, loads=loads, buckets_by_host=buckets) == "h0"
    assert place(128, loads=loads, buckets_by_host=buckets) == "h1"
    # the load-skew bound: a host too far above the floor loses the
    # co-location claim
    assert place(32, loads={"h0": 9, "h1": 2},
                 buckets_by_host=buckets, max_skew=4) == "h1"
    # no bucket info, or the 'load' arm: pure least-loaded (PR 5)
    assert place(None, loads={"h0": 3, "h1": 1},
                 buckets_by_host=buckets) == "h1"
    assert place(32, loads={"h0": 3, "h1": 1}, buckets_by_host=buckets,
                 policy="load") == "h1"
    # deterministic tie-break on host id
    assert place(64, loads={"h0": 1, "h1": 1},
                 buckets_by_host={"h0": {}, "h1": {}}) == "h0"
    with pytest.raises(ValueError, match="policy"):
        place(32, loads=loads, buckets_by_host=buckets, policy="x")
    with pytest.raises(ValueError, match="live hosts"):
        place(32, loads={}, buckets_by_host={})


def test_place_user_is_pure_function_of_journal_state(tmp_path):
    """The replay-determinism pin: two independent replays of the same
    journal drive identical placement decisions."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        for i, pool in enumerate((30, 100, 30, 100, 30)):
            j.append("enqueue", f"u{i}", pool=pool)
        j.append("assign", "u0", host="h0")
        j.append("assign", "u1", host="h1")
    unresolved = {f"u{i}" for i in range(5)}
    decisions = []
    for _ in range(2):
        st = AdmissionJournal(jp).state
        decisions.append([
            place_user(u, state=st, unresolved=unresolved,
                       hosts=["h0", "h1"]) for u in sorted(unresolved)])
    assert decisions[0] == decisions[1]
    st = AdmissionJournal(jp).state
    assert st.pools == {"u0": 30, "u1": 100, "u2": 30, "u3": 100,
                        "u4": 30}
    # u2 (32-bucket) joins u0 on h0; u3 (128-bucket) joins u1 on h1
    assert place_user("u2", state=st, unresolved=unresolved,
                      hosts=["h0", "h1"]) == "h0"
    assert place_user("u3", state=st, unresolved=unresolved,
                      hosts=["h0", "h1"]) == "h1"


def test_plan_failover_colocates_victims_by_bucket(tmp_path):
    """The batched-failover regression (ROADMAP elastic follow-on (c)):
    two same-bucket victims of ONE dead host co-locate — the batch
    planner folds each placement into the next decision's view, and
    plans bucket-grouped so the re-admission order (in-flight first,
    buckets interleaved) cannot split a group at a skew boundary."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        for u, pool in (("a", 30), ("b", 100), ("c", 30), ("d", 100)):
            j.append("enqueue", u, pool=pool)
            j.append("assign", u, host="h0")  # all on the dead host
    st = AdmissionJournal(jp).state
    unresolved = {"a", "b", "c", "d"}
    # victim order interleaves buckets (in-flight-first does this);
    # the PLAN still pairs the 32-bucket users on one host and the
    # 128-bucket users on the other, and keeps the caller's order
    plan = plan_failover(["a", "b", "c", "d"], state=st,
                         unresolved=unresolved, hosts=["h1", "h2"])
    assert [u for u, _ in plan] == ["a", "b", "c", "d"]
    t = dict(plan)
    assert t["a"] == t["c"] and t["b"] == t["d"]
    assert t["a"] != t["b"]  # the pairs split across the survivors
    # deterministic: two replays of the same journal agree
    st2 = AdmissionJournal(jp).state
    assert plan == plan_failover(["a", "b", "c", "d"], state=st2,
                                 unresolved=unresolved,
                                 hosts=["h1", "h2"])
    # the 'load' arm and bucketless users degrade to least-loaded
    plan_ll = plan_failover(["a", "b"], state=st, unresolved=unresolved,
                            hosts=["h1", "h2"], policy="load")
    assert dict(plan_ll) == {"a": "h1", "b": "h2"}


def test_plan_rebalance_moves_queue_tails_to_floor():
    moves = plan_rebalance(
        "h2", loads={"h0": 4, "h1": 3, "h2": 0},
        queued_by_host={"h0": ["a", "b", "c"], "h1": ["d", "e"]})
    # floor share = 7 // 3 = 2: two moves, LAST-enqueued first, from the
    # most-loaded donor; earliest-enqueued users never move (they keep
    # their run-first position)
    assert moves == [("c", "h0"), ("e", "h1")]
    assert plan_rebalance("h2", loads={"h0": 1, "h2": 0},
                          queued_by_host={"h0": ["a"]}) == []
    # donors cap at their own floor: nothing moves a host below it
    assert plan_rebalance(
        "h1", loads={"h0": 2, "h1": 0},
        queued_by_host={"h0": ["a", "b"]}) == [("b", "h0")]
    # deterministic across calls
    kw = dict(loads={"h0": 5, "h1": 5, "h2": 0},
              queued_by_host={"h0": ["a", "b"], "h1": ["c", "d"]})
    assert plan_rebalance("h2", **kw) == plan_rebalance("h2", **kw)


# -- journal records + validator (pure host) -------------------------------


def test_journal_spawn_join_records_replay_fleet_shape(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        j.append("lease", host="h0", pid=1)
        j.append("lease", host="h1", pid=2)
        j.append("join", host="h1")
        j.append("revoke", host="h0", reason="drill")
        j.append("spawn", host="h2", reason="replace")
        j.append("lease", host="h2", pid=3)
        j.append("spawn", host="h3", reason="scale_up")  # never came up
    st = AdmissionJournal(jp).state
    assert st.fleet_hosts() == ["h1", "h2", "h3"]
    assert st.live_hosts() == ["h1", "h2"]  # join counts as live
    rt = JournalState.from_dict(st.to_dict())
    assert rt.fleet_hosts() == st.fleet_hosts()
    with pytest.raises(ValueError, match="needs host"):
        AdmissionJournal(None).append("spawn")


def test_journal_drop_records_keep_dispositions(tmp_path):
    """A drop ack never changes whether a user is queued — it is pure
    rebalance bookkeeping with a cursor, torn-tail tolerant like every
    other record."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        j.append("enqueue", "a", pool=30)
        j.append("assign", "a", host="h0")
        j.append("drop", "a", host="h0", src_off=64, ok=True)
        j.append("assign", "a", host="h1")
    with open(jp, "ab") as f:
        f.write(b'{"event": "drop", "user"')  # torn mid-append
    st = AdmissionJournal(jp).state
    assert st.queued == ["a"] and st.assigned == {"a": "h1"}
    assert st.host_cursor == {"h0": 64}
    assert st.pools == {"a": 30}
    rt = JournalState.from_dict(json.loads(json.dumps(st.to_dict())))
    assert rt.pools == st.pools and rt.queued == st.queued


def test_journal_drain_records_retire_fleet_shape(tmp_path):
    """``drain`` takes the host out of the replayed fleet shape the
    moment it journals (a SIGKILLed coordinator must not respawn shed
    capacity), ``drain_done`` closes the ledger, and ``fence`` acks are
    disposition-neutral routing bookkeeping like ``drop``."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        for h in ("h0", "h1", "h2"):
            j.append("lease", host=h)
            j.append("join", host=h)
        j.append("enqueue", "a", pool=30)
        j.append("admit", "a")
        j.append("assign", "a", host="h2")
        j.append("drain", host="h2")
        # the in-flight user fences off the draining host...
        j.append("fence", "a", host="h2", src_off=32, ok=True, gen=2)
        j.append("assign", "a", host="h0")
        j.append("drain_done", host="h2")
    st = AdmissionJournal(jp).state
    # shape: the drained host is OUT (and was out mid-drain too)
    assert st.fleet_hosts() == ["h0", "h1"]
    assert st.draining_hosts() == []
    # the fence never changed the user's disposition; the assign moved it
    assert st.in_flight == ["a"] and st.assigned == {"a": "h0"}
    assert st.host_cursor == {"h2": 32}
    # a kill BETWEEN drain and drain_done: the shape is already final
    with AdmissionJournal(jp) as j:
        j.append("drain", host="h1")
    st2 = AdmissionJournal(jp).state
    assert st2.fleet_hosts() == ["h0"]
    assert st2.draining_hosts() == ["h1"]
    rt = JournalState.from_dict(st2.to_dict())
    assert rt.fleet_hosts() == st2.fleet_hosts()
    assert validate_journal_file(jp) == []
    with pytest.raises(ValueError, match="needs host"):
        AdmissionJournal(None).append("drain")


def test_validate_journal_file(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        j.append("enqueue", "a", pool=30)
        j.append("spawn", host="h0", reason="replace")
        j.append("admit", "a")
    assert validate_journal_file(jp) == []
    with open(jp, "ab") as f:
        f.write(b'{"event": "admit"')  # torn tail: allowed
    assert validate_journal_file(jp) == []
    with open(jp, "ab") as f:
        f.write(b'\n{"event": "nonsense", "user": "a", "seq": 9}\n')
        f.write(b'{"event": "admit", "user": "a", "seq": 1}\n')
    errs = validate_journal_file(jp)
    assert any("unknown event" in e for e in errs)
    assert any("seq regressed" in e for e in errs)
    assert validate_journal_file(str(tmp_path / "missing.jsonl"))


# -- batch-reserve admission (planner follow-on (b)) -----------------------


class _E:
    def __init__(self, uid, priority="batch"):
        self.user_id = uid
        self.priority = priority


def test_queue_batch_reserve_starvation_bound():
    """The starvation bound: with one slot reserved, an interactive
    surge occupies at most target_live - 1 slots — the LAST free slot
    only ever admits the batch waiter, within ONE slot turnover instead
    of aging_s."""
    q = AdmissionQueue(16, reserve={"batch": 1})
    for e in (_E("b0"), _E("i0", "interactive"), _E("i1", "interactive"),
              _E("i2", "interactive")):
        q.put(e)
    # free slots above the unmet reserve: strict priority as usual
    assert q.pop(live={}, free=4)[0].user_id == "i0"
    assert q.pop(live={"interactive": 1}, free=3)[0].user_id == "i1"
    assert q.pop(live={"interactive": 2}, free=2)[0].user_id == "i2"
    # the last slot is the reserve's: batch pops ahead of any surge
    q.put(_E("i3", "interactive"))
    assert q.pop(live={"interactive": 3}, free=1)[0].user_id == "b0"
    # reserve satisfied -> strict priority returns
    assert q.pop(live={"interactive": 3, "batch": 1},
                 free=1)[0].user_id == "i3"
    # no batch waiters: the reserve never blocks a pop
    q2 = AdmissionQueue(8, reserve={"batch": 1})
    q2.put(_E("i0", "interactive"))
    assert q2.pop(live={}, free=1)[0].user_id == "i0"
    # legacy pop() (no slot context) keeps the pre-reserve behavior
    q3 = AdmissionQueue(8, reserve={"batch": 1})
    q3.put(_E("b0"))
    q3.put(_E("i0", "interactive"))
    assert q3.pop()[0].user_id == "i0"
    with pytest.raises(ValueError, match="batch_reserve"):
        ServeConfig(batch_reserve=-1)


def test_queue_remove_withdraws_only_queued():
    q = AdmissionQueue(8)
    q.put(_E("a"))
    q.put(_E("b", "interactive"))
    assert q.remove("b").user_id == "b"
    assert q.remove("b") is None  # gone
    assert q.remove("zz") is None  # never queued
    assert len(q) == 1 and q.pop()[0].user_id == "a"


# -- telemetry-sized dispatch holds (planner follow-on (d)) ----------------


def test_dispatch_hold_step_ema_decision_table():
    kw = dict(waiting=2, host_in_flight=1, headroom_s=10.0,
              max_hold_s=1.0)
    # no telemetry yet: the structural cap (unchanged behavior)
    assert dispatch_hold(**kw) == 1.0
    # observed host steps SIZE the hold — shorter than the cap when the
    # steps are fast, longer when they are slow (still inside headroom)
    assert dispatch_hold(step_ema_s=0.04, **kw) == pytest.approx(0.04)
    assert dispatch_hold(step_ema_s=3.0, **kw) == 3.0
    assert dispatch_hold(step_ema_s=30.0, **kw) == 10.0  # SLO bound
    # the structural zeros still win
    assert dispatch_hold(waiting=0, host_in_flight=1, headroom_s=10.0,
                         max_hold_s=1.0, step_ema_s=0.5) == 0.0
    assert dispatch_hold(waiting=2, host_in_flight=0, headroom_s=10.0,
                         max_hold_s=1.0, step_ema_s=0.5) == 0.0
    assert dispatch_hold(waiting=2, host_in_flight=1, headroom_s=0.0,
                         max_hold_s=1.0, step_ema_s=0.5) == 0.0
    # max_hold_s=0 stays the operator OFF switch even with telemetry
    assert dispatch_hold(waiting=2, host_in_flight=1, headroom_s=10.0,
                         max_hold_s=0.0, step_ema_s=0.5) == 0.0


def test_planner_note_host_step_sizes_window():
    cfg = ServeConfig(slo_interactive_s=100.0, slo_batch_s=100.0,
                      max_hold_s=1.0)
    p = AdmissionPlanner(cfg, router=BucketRouter(), clock=lambda: 0.0)
    assert p.window_s(2, 1) == 1.0  # no telemetry: the cap
    p.note_host_step(0.05)
    assert p.window_s(2, 1) == pytest.approx(0.05)
    p.note_host_step(0.05)
    ema = 0.3 * 0.05 + 0.7 * 0.05
    assert p.window_s(2, 1) == pytest.approx(ema)
    assert p.summary()["host_step_ema_s"] == pytest.approx(ema, abs=1e-4)
    # the scheduler seam: completed host futures feed the EMA
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler
    from tests.test_fleet import _cfg

    sched = FleetScheduler(_cfg(), report=FleetReport(), hold=p)
    assert sched.hold is p and callable(sched.hold.note_host_step)


# -- fleet planner (merged sketches) ---------------------------------------


def _sketch_of(vals):
    sk = QuantileSketch()
    for v in vals:
        sk.add(int(v))
    return sk


def test_sketch_merge_all_matches_chained_merges():
    parts = [[30] * 5, [100] * 3, [480] * 2]
    dicts = [_sketch_of(p).to_dict() for p in parts]
    folded = QuantileSketch.merge_all(dicts)
    chained = QuantileSketch.from_dict(dicts[0]).merge(
        QuantileSketch.from_dict(dicts[1])).merge(
        QuantileSketch.from_dict(dicts[2]))
    assert folded._buckets == chained._buckets
    assert (folded.n, folded.min, folded.max) \
        == (chained.n, chained.min, chained.max)
    assert QuantileSketch.merge_all([]).n == 0


def test_fleet_planner_merges_derives_journals_and_restores(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        fp = FleetPlanner(j, epoch=4)
        fp.note_host_sketch("h0", _sketch_of([120] * 4).to_dict())
        edges1 = fp.poll()
        assert edges1 and fp.edges == edges1
        assert edges1 == derive_edges(_sketch_of([120] * 4), n_buckets=4)
        # below the next epoch: no re-derivation
        fp.note_host_sketch("h1", _sketch_of([480]).to_dict())
        assert fp.poll() is None
        fp.note_host_sketch("h1", _sketch_of([480] * 4).to_dict())
        edges2 = fp.poll()
        assert edges2 and 480 in edges2
        assert fp.summary()["hosts_sketching"] == ["h0", "h1"]
    # the journaled fleet epochs restore: a restarted coordinator
    # rebroadcasts the killed run's edges before any new telemetry
    with AdmissionJournal(jp) as j2:
        fp2 = FleetPlanner(j2, epoch=4)
        assert fp2.edges == edges2
        assert fp2.merged().n == 8
    assert validate_journal_file(jp) == []


# -- deterministic fake-worker drills --------------------------------------


class _FakeWorker:
    """The test plays one worker host: beats the lease, consumes the
    assignment feed, appends admit/finish/drop-ack/planner records to
    the event WAL — everything journal/file-driven, nothing timed, so
    the coordinator's join/rebalance/broadcast machinery is exercised
    deterministically in-process."""

    def __init__(self, fabric_dir, host_id):
        self.host_id = host_id
        self.paths = fabric_paths(fabric_dir, host_id)
        self.feed = JsonlTail(self.paths["assign"])
        self.queued: list = []
        self.admitted: list = []
        self.finished: list = []
        self.edges: list = []
        self.dead = False
        self.draining = False
        #: fence requests deferred to the next checkpoint "boundary"
        #: (the test script calls release() to model it)
        self.fence_pending: list = []
        self._rc = None
        self.beat()

    # Popen-shaped surface the coordinator drives
    @property
    def pid(self):
        return os.getpid()

    def poll(self):
        return self._rc

    def kill(self):
        self._rc = -9
        self.dead = True

    def wait(self, timeout=None):
        return self._rc

    def beat(self):
        if self.dead:
            return
        tmp = self.paths["lease"] + ".tmp"
        with open(tmp, "wb") as f:
            f.write(json.dumps(
                {"host": self.host_id, "pid": os.getpid(),
                 "t": time.time()}).encode())
        os.replace(tmp, self.paths["lease"])

    def _event(self, rec):
        with open(self.paths["events"], "ab") as f:
            f.write((json.dumps(rec) + "\n").encode())

    def pump(self):
        """One worker round: drain the feed, ack drops, admit nothing
        (the test script decides when to admit/finish)."""
        if self.dead:
            return
        self.beat()
        for rec, _off in self.feed.poll():
            if rec.get("close"):
                self._rc = 0
                continue
            if isinstance(rec.get("edges"), list):
                self.edges.append(tuple(rec["edges"]))
                continue
            if rec.get("drain"):
                self.draining = True  # stop admitting; keep the feed
                continue
            if rec.get("fence") is not None:
                uid = str(rec["fence"])
                if uid in self.queued:  # still queued: withdraw now
                    self.queued.remove(uid)
                    self._event({"event": "fence", "user": uid,
                                 "ok": True})
                elif uid in self.admitted:  # release at next boundary
                    self.fence_pending.append(uid)
                else:
                    self._event({"event": "fence", "user": uid,
                                 "ok": False})
                continue
            if rec.get("drop") is not None:
                uid = str(rec["drop"])
                ok = uid in self.queued
                if ok:
                    self.queued.remove(uid)
                self._event({"event": "drop", "user": uid, "ok": ok})
                continue
            if rec.get("user") is not None:
                self.queued.append(str(rec["user"]))
        if self.draining and not self.queued and not self.admitted \
                and not self.fence_pending and self._rc is None:
            self._rc = 0  # the real worker's serve loop exits here

    def admit(self, uid):
        self.queued.remove(uid)
        self.admitted.append(uid)
        self._event({"event": "admit", "user": uid})

    def release(self, uid, gen=1):
        """Model the checkpoint-boundary fence release: the user leaves
        the engine with its workspace committed at ``gen``."""
        self.admitted.remove(uid)
        self.fence_pending.remove(uid)
        self._event({"event": "fence", "user": uid, "ok": True,
                     "gen": gen})

    def finish(self, uid):
        self.admitted.remove(uid)
        self.finished.append(uid)
        self._event({"event": "finish", "user": uid})

    def journal_sketch(self, pools):
        self._event({"event": "planner", "edges": [],
                     "sketch": _sketch_of(pools).to_dict()})


def _fake_fleet(tmp_path, config, users, pools, script, tracer=None,
                status=None, alerts=None):
    """Run a coordinator over fake workers; ``script(round, coord,
    workers)`` drives the scenario each poll and returns True to keep
    going.  ``tracer``/``status``/``alerts``: the introspection-plane
    limbs (``tests/test_introspection.py`` passes them; the base drills
    run bare)."""
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir, exist_ok=True)
    journal = AdmissionJournal(
        os.path.join(fabric_dir, "serve_journal.jsonl"))
    workers: dict = {}

    def spawn(host_id):
        workers[host_id] = _FakeWorker(fabric_dir, host_id)
        return workers[host_id]

    state = {"round": 0}

    def on_poll(coord):
        state["round"] += 1
        if state["round"] > 2000:
            raise AssertionError("fake drill wedged: "
                                 f"unresolved={sorted(coord._unresolved)}")
        for w in list(workers.values()):
            w.pump()
        script(state["round"], coord, workers)

    coord = FabricCoordinator(journal, fabric_dir, config,
                              on_poll=on_poll, tracer=tracer,
                              status=status, alerts=alerts)
    try:
        summary = coord.run(users, spawn, pools=pools)
    finally:
        journal.close()
        if tracer is not None:
            tracer.close()
    return summary, coord, workers, fabric_dir


def test_elastic_join_rebalance_and_fleet_edges(tmp_path):
    """The deterministic JOIN drill: a backlogged 1-host elastic fabric
    scales up, the joiner is journaled (spawn + join), queued users
    migrate onto it through the drop-ack protocol (never the admitted
    one), and the fleet planner's merged edges broadcast identically to
    every host."""
    users = [f"u{i}" for i in range(6)]
    pools = {u: (30 if i % 2 == 0 else 100) for i, u in enumerate(users)}
    # drain_timeout_s is tiny because nothing pumps the fakes once the
    # run loop exits — the close-path SIGKILL is cosmetic (PR 5 contract)
    cfg = FabricConfig(hosts=1, min_hosts=1, max_hosts=2,
                       scale_backlog=2, poll_s=0.01, lease_s=5.0,
                       planner_epoch=4, drain_timeout_s=0.2)

    def script(rnd, coord, workers):
        h0 = workers.get("h0")
        if rnd == 2 and h0 and not h0.admitted and h0.queued:
            h0.admit(h0.queued[0])  # one in-flight: must never migrate
        if rnd == 4 and h0:
            # per-host sketches -> the fleet planner derives + broadcasts
            h0.journal_sketch([pools[u] for u in users])
        if rnd > 6:
            for w in workers.values():
                for uid in list(w.admitted):
                    w.finish(uid)
                for uid in list(w.queued):
                    w.admit(uid)

    summary, coord, workers, fabric_dir = _fake_fleet(
        tmp_path, cfg, users, pools, script)
    assert sorted(summary["finished"]) == users
    assert summary["spawns"] >= 1 and summary["joins"] >= 1
    assert summary["migrations"] >= 1
    assert set(workers) == {"h0", "h1"}
    # the drop-ack protocol: every migrated user ran on exactly one host
    ran = [u for w in workers.values() for u in w.finished]
    assert sorted(ran) == users
    # fleet edges broadcast identically to every live host
    fp = summary["fleet_planner"]
    assert fp["edges"]
    for w in workers.values():
        if w.edges:
            assert w.edges[-1] == tuple(fp["edges"])
    # the journal replays the grown fleet shape + the pools
    st = AdmissionJournal(
        os.path.join(fabric_dir, "serve_journal.jsonl")).state
    assert st.fleet_hosts() == ["h0", "h1"]
    assert st.pools == pools
    assert validate_journal_file(
        os.path.join(fabric_dir, "serve_journal.jsonl")) == []


def test_elastic_coordinator_kill_mid_rebalance_replays(tmp_path):
    """Coordinator SIGKILL mid-rebalance (drop requested, ack not yet
    transcribed) replays to the same assignments: the rerun re-derives
    placement from the journal alone, every user finishes exactly once,
    and two further replays of the final journal agree on every
    assignment."""
    users = [f"u{i}" for i in range(6)]
    pools = {u: 30 for u in users}
    cfg = FabricConfig(hosts=1, min_hosts=1, max_hosts=2,
                       scale_backlog=2, poll_s=0.01,
                       drain_timeout_s=0.2)
    jp = str(tmp_path / "fabric" / "serve_journal.jsonl")

    def script1(rnd, coord, workers):
        # the moment migrate requests are pending, die — the acks are
        # stranded in h0's feed/WAL, the journal still says "assigned h0"
        if coord._migrating:
            raise InjectedKill("coordinator SIGKILL mid-rebalance")

    with pytest.raises(InjectedKill):
        _fake_fleet(tmp_path, cfg, users, pools, script1)
    st_mid = AdmissionJournal(jp).state
    assert st_mid.fleet_hosts() == ["h0", "h1"]  # shape already journaled
    assigned_mid = dict(st_mid.assigned)
    assert assigned_mid  # routing decisions survived the kill

    def script2(rnd, coord, workers):
        if rnd > 4:
            for w in workers.values():
                for uid in list(w.admitted):
                    w.finish(uid)
                for uid in list(w.queued):
                    w.admit(uid)

    summary, coord, workers, _ = _fake_fleet(
        tmp_path, cfg, users, pools, script2)
    assert sorted(summary["finished"]) == users
    # the rerun replayed the SAME fleet shape (h1 respawned from its
    # journaled spawn record, not re-decided)
    assert set(workers) == {"h0", "h1"}
    ran = [u for w in workers.values() for u in w.finished]
    assert sorted(ran) == users  # exactly-once, no double-run
    # replay determinism: two independent replays agree on assignments
    a1 = AdmissionJournal(jp).state.assigned
    a2 = AdmissionJournal(jp).state.assigned
    assert a1 == a2


def _drain_script(rnd, coord, workers):
    """The canonical drain scenario: each host admits one user early
    (so the victim holds an in-flight user), fenced users release at
    their next round ('boundary'), and once the drain has been decided
    the surviving hosts work normally."""
    if rnd == 2:
        for w in workers.values():
            if w.queued and not w.dead:
                w.admit(w.queued[0])
    for w in workers.values():
        for uid in list(w.fence_pending):
            w.release(uid, gen=1)
    live = sum(1 for h in coord.hosts.values() if h.alive)
    if coord.drains or live <= coord.config.min_hosts:
        # hold work until the drain decision (run 1 keeps its loads
        # stable so the victim choice is scripted); a rerun already AT
        # min_hosts — the post-kill replay — just works
        for w in workers.values():
            if w.dead or w.draining:
                continue
            for uid in list(w.admitted):
                w.finish(uid)
            for uid in list(w.queued):
                w.admit(uid)


def test_elastic_scale_down_drain_rebalance_exit(tmp_path):
    """The deterministic DRAIN drill: a quiet 2-host elastic fabric
    scales down — the drain is journaled, the victim's queued users
    rebalance away over the drop-ack path, its in-flight user migrates
    via the checkpoint fence (released at its boundary, re-assigned
    only on the journaled ack), the host exits clean and retires with
    ``drain_done`` — and every user finishes on exactly one host."""
    users = [f"u{i}" for i in range(6)]
    pools = {u: (30 if i % 2 == 0 else 100)
             for i, u in enumerate(users)}
    cfg = FabricConfig(hosts=2, min_hosts=1, max_hosts=2,
                       scale_down_s=0.05, poll_s=0.01,
                       drain_timeout_s=0.2)

    summary, coord, workers, fabric_dir = _fake_fleet(
        tmp_path, cfg, users, pools, _drain_script)
    assert sorted(summary["finished"]) == users
    assert summary["drains"] == 1
    assert summary["fences"] >= 1  # the in-flight user migrated
    assert summary["migrations"] >= 1
    assert "drained" in summary["hosts"].values()
    assert "revoked" not in summary["hosts"].values()
    # exactly-one-owner: every user finished on exactly ONE host, and
    # the fenced user was released (never finished) on the victim
    ran = [u for w in workers.values() for u in w.finished]
    assert sorted(ran) == users
    # the journal narrative: drain then drain_done for the victim, and
    # the replayed fleet shape is the post-drain fleet
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    st = AdmissionJournal(jp).state
    victim = [h for h, s in summary["hosts"].items()
              if s == "drained"][0]
    assert st.hosts[victim] == "drain_done"
    assert victim not in st.fleet_hosts()
    assert len(st.fleet_hosts()) == 1
    assert validate_journal_file(jp) == []
    # the drain did NOT redo work: the fence ack carried a generation
    # and the user resumed, it was never run twice to completion
    assert len(ran) == len(set(ran))


def test_source_worker_sigkill_mid_drain_fails_over(tmp_path):
    """The OTHER kill axis: the draining SOURCE worker dies after the
    fence was requested but before it released — failover supersedes
    the graceful path (revoke, not drain_done; the pending fence is
    discarded), the victims re-place as one batch, and every user still
    finishes exactly once."""
    users = [f"u{i}" for i in range(6)]
    pools = {u: 30 for u in users}
    cfg = FabricConfig(hosts=2, min_hosts=1, max_hosts=2,
                       scale_down_s=0.05, poll_s=0.01,
                       drain_timeout_s=0.2)

    def script(rnd, coord, workers):
        if rnd == 2:
            for w in workers.values():
                if w.queued and not w.dead:
                    w.admit(w.queued[0])
        # the moment a fence request reaches the draining worker, KILL
        # it instead of releasing — the in-flight user's workspace is
        # the failover resume unit
        for w in workers.values():
            if w.fence_pending and not w.dead:
                w.kill()
        live = sum(1 for h in coord.hosts.values() if h.alive)
        if coord.revocations or live <= coord.config.min_hosts:
            for w in workers.values():
                if w.dead or w.draining:
                    continue
                for uid in list(w.admitted):
                    w.finish(uid)
                for uid in list(w.queued):
                    w.admit(uid)

    summary, coord, workers, fabric_dir = _fake_fleet(
        tmp_path, cfg, users, pools, script)
    assert sorted(summary["finished"]) == users
    assert summary["drains"] == 1
    assert summary["revocations"] == 1  # the kill superseded the drain
    assert summary["fences"] == 0  # no ack ever landed
    assert "revoked" in summary["hosts"].values()
    ran = [u for w in workers.values() for u in w.finished]
    assert sorted(ran) == users  # exactly once, on the survivor
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    st = AdmissionJournal(jp).state
    victim = [h for h, s in summary["hosts"].items()
              if s == "revoked"][0]
    assert st.hosts[victim] == "revoke"  # not drain_done
    assert validate_journal_file(jp) == []


@pytest.mark.parametrize("point", ["fabric.drain",
                                   "fabric.migrate.fence",
                                   "fabric.migrate.commit"])
def test_scale_down_kill_matrix_replays_single_owner(tmp_path, point):
    """Coordinator SIGKILL at every new fault point: the rerun replays
    to a fleet at ``min_hosts`` with every user finishing EXACTLY once
    (the single-owner invariant, asserted across both incarnations'
    workers), and the final journal validates."""
    users = [f"u{i}" for i in range(4)]
    pools = {u: 30 for u in users}
    cfg = FabricConfig(hosts=2, min_hosts=1, max_hosts=2,
                       scale_down_s=0.05, poll_s=0.01,
                       drain_timeout_s=0.2)
    jp = str(tmp_path / "fabric" / "serve_journal.jsonl")

    first: dict = {}

    def script1(rnd, coord, workers):
        first.update(workers)
        _drain_script(rnd, coord, workers)

    with faults.inject(FaultRule(point, "kill", at=1)):
        with pytest.raises(InjectedKill):
            _fake_fleet(tmp_path, cfg, users, pools, script1)
    st_mid = AdmissionJournal(jp).state
    if point == "fabric.drain":
        # killed BEFORE the decision journaled: the full fleet replays
        assert len(st_mid.fleet_hosts()) == 2
    else:
        # the drain record is durable: shed capacity stays shed
        assert len(st_mid.fleet_hosts()) + len(st_mid.draining_hosts()) \
            == 2

    summary, coord, workers, _ = _fake_fleet(
        tmp_path, cfg, users, pools, _drain_script)
    assert summary["failed"] == [] and summary["poisoned"] == []
    # exactly-one-owner across BOTH incarnations: the fenced user never
    # completed on two hosts (users finished before the kill are
    # skip_done on resubmit and must NOT re-run)
    ran = [u for w in list(first.values()) + list(workers.values())
           for u in w.finished]
    assert sorted(ran) == users
    st = AdmissionJournal(jp).state
    assert st.finished == set(users) and not st.pending
    assert len(st.fleet_hosts()) == cfg.min_hosts
    assert st.draining_hosts() == []
    assert validate_journal_file(jp) == []


def test_elastic_stillborn_spawns_raise_instead_of_fork_storming(
        tmp_path):
    """The crash-loop guard: workers that die before their first
    heartbeat must not be respawned at poll rate forever — after 3
    consecutive stillborn spawns the coordinator raises FabricError
    (all state durable; the non-elastic fabric's safety semantics)."""
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    journal = AdmissionJournal(
        os.path.join(fabric_dir, "serve_journal.jsonl"))
    spawned = []

    class _Stillborn:
        pid = None

        def poll(self):
            return 1  # exits instantly, never heartbeats

        def kill(self):
            pass

        def wait(self, timeout=None):
            return 1

    def spawn(host_id):
        spawned.append(host_id)
        return _Stillborn()

    coord = FabricCoordinator(
        journal, fabric_dir,
        FabricConfig(hosts=1, min_hosts=1, max_hosts=2, poll_s=0.01,
                     drain_timeout_s=0.1))
    with pytest.raises(Exception, match="first heartbeat"):
        coord.run(["u0"], spawn)
    journal.close()
    # bounded respawns (initial + guarded replacements), not poll-rate
    assert 1 <= len(spawned) <= 6


def test_elastic_operator_adoption_unit(tmp_path):
    """An operator-added worker announces via the lease directory: a
    fresh lease for an unknown host id is adopted (spawn reason
    'operator' + lease journaled, pid-only handle), a stale one is
    ignored."""
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    journal = AdmissionJournal(
        os.path.join(fabric_dir, "serve_journal.jsonl"))
    cfg = FabricConfig(hosts=1, min_hosts=1, max_hosts=3, poll_s=0.01)
    coord = FabricCoordinator(journal, fabric_dir, cfg)
    volunteer = subprocess.Popen([sys.executable, "-c",
                                  "import time; time.sleep(60)"])
    try:
        for hid, pid, fresh in (("h7", volunteer.pid, True),
                                ("h8", volunteer.pid, False)):
            lease = fabric_paths(fabric_dir, hid)["lease"]
            t = time.time() - (0.0 if fresh else 3600.0)
            with open(lease, "wb") as f:
                f.write(json.dumps({"host": hid, "pid": pid,
                                    "t": t}).encode())
        coord._adopt_operator_hosts()
        assert "h7" in coord.hosts and "h8" not in coord.hosts
        assert coord.hosts["h7"].proc.poll() is None  # pid supervised
        st = journal.state
        assert st.hosts["h7"] == "lease"
        assert coord.spawns == 1
    finally:
        volunteer.kill()
        volunteer.wait()
        journal.close()
    st = AdmissionJournal(
        os.path.join(fabric_dir, "serve_journal.jsonl")).state
    assert "h7" in st.fleet_hosts()


# -- the real-subprocess respawn drill (the acceptance pin) ----------------


def _spawn_factory(fabric_dir, ws_root, cfg, specs, *, lease_s=5.0,
                   target=2, faults_spec=None):
    def spawn(host_id):
        log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
        env = {**os.environ, "PYTHONPATH": REPO}
        env.pop("CETPU_FAULTS", None)
        if faults_spec:
            # e.g. a pool.score delay=S straggler rule: slows every
            # worker iteration without touching any journaled value
            env["CETPU_FAULTS"] = faults_spec
        try:
            return subprocess.Popen(
                [sys.executable, WORKER, fabric_dir, host_id, ws_root,
                 cfg.mode, str(cfg.epochs), str(len(specs)),
                 str(lease_s), str(target), sizes_arg(specs)],
                stdout=log, stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()
    return spawn


def _kill_on_first_admit(host_id="h0"):
    state = {"done": False}

    def chaos(coord):
        if state["done"]:
            return
        st = coord.journal.state
        if any(h == host_id and st.last.get(u) == "admit"
               for u, h in st.assigned.items()):
            coord.hosts[host_id].proc.kill()
            state["done"] = True
    return chaos


def _deadline(inner, deadline_s=300.0):
    t0 = time.monotonic()

    def hook(coord):
        if time.monotonic() - t0 > deadline_s:
            raise AssertionError(
                f"elastic drill exceeded {deadline_s}s; "
                f"unresolved={sorted(coord._unresolved)}")
        inner(coord)
    return hook


def _elastic_kill_drill(tmp_path, mode, *, n_users=4, epochs=2):
    """SIGKILL one worker of a 2-host ELASTIC fabric mid-run: the
    autoscaler must respawn a replacement (fresh id, lease re-granted,
    spawn journaled), every user must finish bit-identical to
    uninterrupted sequential runs, and the journal must replay the grown
    fleet shape."""
    cfg = make_cfg(mode, epochs=epochs)
    specs = user_specs(n_users, sizes=[30, 100])
    seq = sequential_baselines(str(tmp_path), cfg, specs)
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    journal = AdmissionJournal(jp)
    coord = FabricCoordinator(
        journal, fabric_dir,
        FabricConfig(hosts=2, min_hosts=2, max_hosts=3, lease_s=5.0),
        on_poll=_deadline(_kill_on_first_admit("h0")))
    try:
        summary = coord.run(
            [u for _, u, _ in specs],
            _spawn_factory(fabric_dir, str(tmp_path), cfg, specs),
            pools={u: n for _, u, n in specs})
    finally:
        journal.close()
    assert sorted(summary["finished"]) == sorted(u for _, u, _ in specs)
    assert summary["failed"] == [] and summary["poisoned"] == []
    assert summary["revocations"] == 1
    # THE elastic pin: dead capacity was REPLACED, not folded onto the
    # survivor forever — h2 spawned the moment h0 was revoked
    assert summary["spawns"] >= 1
    assert "h2" in summary["hosts"]
    assert summary["hosts"]["h0"] == "revoked"
    results = read_results(fabric_dir)
    for _, uid, _ in specs:
        assert results[uid]["error"] is None
        assert results[uid]["result"]["trajectory"] \
            == seq[uid]["trajectory"]
        assert results[uid]["result"]["final_mean_f1"] \
            == seq[uid]["final_mean_f1"]
    st = AdmissionJournal(jp).state
    assert st.finished == {u for _, u, _ in specs} and not st.pending
    assert st.hosts["h0"] == "revoke"
    assert set(st.fleet_hosts()) >= {"h1", "h2"}
    assert validate_journal_file(jp) == []
    return summary


def test_elastic_worker_sigkill_respawns_and_recovers(tmp_path):
    """Tier-1 acceptance: worker SIGKILL → autoscaler respawn → all
    users recovered bit-identical, fleet shape replayable."""
    _elastic_kill_drill(tmp_path, "mc")


def _scale_down_drill(tmp_path, mode, *, n_users=6, epochs=3):
    """A REAL 3-host elastic fabric scales DOWN to 2 hosts mid-run: the
    drain journals, the victim sheds its queued users over the drop-ack
    path and its IN-FLIGHT users over the checkpoint fence, and every
    user ends bit-identical to uninterrupted sequential runs — zero
    loss, no failover, exactly one owner each.  Workers run under a
    ``pool.score`` delay rule (slow-host simulation — values untouched)
    so sessions reliably outlive the fence round-trip."""
    cfg = make_cfg(mode, epochs=epochs)
    specs = user_specs(n_users, sizes=[30, 100])
    seq = sequential_baselines(str(tmp_path), cfg, specs)
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    journal = AdmissionJournal(jp)
    coord = FabricCoordinator(
        journal, fabric_dir,
        FabricConfig(hosts=3, min_hosts=2, max_hosts=3, lease_s=5.0,
                     scale_down_s=600.0, drain_timeout_s=30.0),
        on_poll=_deadline(force_low_water))
    try:
        summary = coord.run(
            [u for _, u, _ in specs],
            _spawn_factory(fabric_dir, str(tmp_path), cfg, specs,
                           faults_spec="pool.score:delay=0.3@1x-1"),
            pools={u: n for _, u, n in specs})
    finally:
        journal.close()
    # zero loss, no failover — the shed was GRACEFUL
    assert sorted(summary["finished"]) == sorted(u for _, u, _ in specs)
    assert summary["failed"] == [] and summary["poisoned"] == []
    assert summary["revocations"] == 0
    assert summary["drains"] >= 1
    # the forced drain landed while the victim held in-flight sessions:
    # at least one moved through the checkpoint fence
    assert summary["fences"] >= 1
    results = read_results(fabric_dir)
    for _, uid, _ in specs:
        assert results[uid]["error"] is None
        assert results[uid]["result"]["trajectory"] \
            == seq[uid]["trajectory"]
        assert results[uid]["result"]["final_mean_f1"] \
            == seq[uid]["final_mean_f1"]
    st = AdmissionJournal(jp).state
    assert st.finished == {u for _, u, _ in specs} and not st.pending
    # the fleet shape scaled down: a drain journaled for some victim,
    # and the replayed shape holds exactly min_hosts survivors
    assert any(e in ("drain", "drain_done") for e in st.hosts.values())
    assert len(st.fleet_hosts()) == 2
    assert validate_journal_file(jp) == []
    return summary


def test_elastic_scale_down_subprocess_drill(tmp_path):
    """Tier-1 acceptance: 3-host elastic fabric scales down to 2 with
    zero user loss, parity bit-identical to sequential."""
    _scale_down_drill(tmp_path, "mc")


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["hc", "wmc"])
def test_scale_down_matrix_other_modes(tmp_path, mode):
    """Scale-down recovery is mode-independent (mc is tier-1 above):
    the registry modes ride the same drain/fence machinery."""
    _scale_down_drill(tmp_path, mode)


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["hc", "wmc"])
def test_elastic_kill_matrix_other_modes(tmp_path, mode):
    """The respawn recovery is mode-independent (mc is tier-1 above):
    the registry modes ride the same journal machinery."""
    _elastic_kill_drill(tmp_path, mode)
