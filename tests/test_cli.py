"""CLI integration: pre-train on a synthetic DEAM, personalize on a synthetic
AMG1608 — the reference's two-command workflow end to end (README.md:43-60
of the reference), host-only committee."""

import json
import os

import numpy as np
import pandas as pd
import pytest
from scipy.io import savemat

from consensus_entropy_tpu.cli import amg_test, deam_classifier

FEATURE_COLS = (["F0final_sma_stddev"] + [f"f{i}" for i in range(6)]
                + ["mfcc_sma_de[14]_amean"])


@pytest.fixture
def synth_roots(tmp_path, rng):
    """A miniature DEAM + AMG1608 on disk, class-separable features."""
    centers = rng.standard_normal((4, len(FEATURE_COLS))) * 3.0

    # --- DEAM: features + dynamic annotations -------------------------
    deam = tmp_path / "deam"
    (deam / "features").mkdir(parents=True)
    (deam / "annotations").mkdir()
    times = np.arange(15.0, 25.0, 0.5)
    cols_ms = [f"sample_{int(t * 1000)}ms" for t in times]
    a_rows, v_rows = [], []
    for sid in range(1, 25):
        target = sid % 4  # song's dominant quadrant
        a_sign = 1.0 if target in (0, 1) else -1.0  # deam geometry
        v_sign = 1.0 if target in (0, 3) else -1.0
        a_vals = a_sign * rng.uniform(0.2, 1.0, len(times))
        v_vals = v_sign * rng.uniform(0.2, 1.0, len(times))
        feats = centers[target] + rng.standard_normal(
            (len(times), len(FEATURE_COLS))).astype(np.float32)
        df = pd.DataFrame(feats, columns=FEATURE_COLS)
        df.insert(0, "frameTime", times)
        df.to_csv(deam / "features" / f"{sid}.csv", sep=";", index=False)
        a_rows.append({"song_id": sid, **dict(zip(cols_ms, a_vals))})
        v_rows.append({"song_id": sid, **dict(zip(cols_ms, v_vals))})
    pd.DataFrame(a_rows).to_csv(deam / "annotations" / "arousal.csv",
                                index=False)
    pd.DataFrame(v_rows).to_csv(deam / "annotations" / "valence.csv",
                                index=False)

    # --- AMG: per-song feature csvs + .mat annotations ----------------
    amg = tmp_path / "amg1608"
    (amg / "feats").mkdir(parents=True)
    (amg / "anno").mkdir()
    n_songs, n_users = 40, 6
    song_ids = np.arange(201, 201 + n_songs)
    song_class = rng.integers(0, 4, size=n_songs)
    for sid, c in zip(song_ids, song_class):
        k = int(rng.integers(4, 8))
        feats = centers[c] + rng.standard_normal(
            (k, len(FEATURE_COLS))).astype(np.float32)
        df = pd.DataFrame(feats, columns=FEATURE_COLS)
        df.insert(0, "frameTime", np.arange(k) * 1.0)
        df.to_csv(amg / "feats" / f"{sid}.csv", sep=";", index=False)
    # annotations: valence/arousal consistent with each song's class (amg
    # geometry, [valence, arousal] order), light per-user noise on magnitude
    lab = np.full((n_songs, n_users, 2), np.nan)
    for i, c in enumerate(song_class):
        a_sign = 1.0 if c in (0, 1) else -1.0
        v_sign = 1.0 if c in (0, 3) else -1.0
        for u in range(n_users):
            if rng.uniform() < 0.9:  # most users annotated most songs
                lab[i, u, 0] = v_sign * rng.uniform(0.3, 1.0)
                lab[i, u, 1] = a_sign * rng.uniform(0.3, 1.0)
    savemat(str(amg / "anno" / "AMG1608.mat"), {"song_label": lab})
    savemat(str(amg / "anno" / "1608_song_id.mat"),
            {"mat_id2song_id": song_ids.reshape(-1, 1)})

    models = tmp_path / "models"
    return {"deam": str(deam), "amg": str(amg), "models": str(models)}


def test_full_workflow(synth_roots, capsys):
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]

    # 1. pre-train three classic members × 2 folds each
    for model in ("gnb", "sgd", "xgb"):
        assert deam_classifier.main(["-cv", "2", "-m", model] + flags) == 0
    pre = os.path.join(synth_roots["models"], "pretrained")
    pkls = [f for f in os.listdir(pre) if f.endswith(".pkl")]
    assert len(pkls) == 6  # 3 algos × 2 folds
    out = capsys.readouterr().out
    assert "CV RESULTS" in out and "F1" in out

    # 2. AL personalization, mc mode, 2 users
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "2"] + flags)
    assert rc == 0
    users_dir = os.path.join(synth_roots["models"], "users")
    users = sorted(os.listdir(users_dir))
    assert len(users) == 2
    udir = os.path.join(users_dir, users[0], "mc")
    assert os.path.exists(os.path.join(udir, "DONE"))
    metrics = [json.loads(l) for l in open(os.path.join(udir,
                                                        "metrics.jsonl"))]
    assert len(metrics) == 3  # epoch0 + 2 AL iterations
    assert len(metrics[-1]["f1"]) == 6  # every committee member evaluated
    # committee was persisted back
    assert any(f.endswith(".pkl") for f in os.listdir(udir))

    # 3. resume: second invocation skips completed users
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "2"] + flags)
    assert rc == 0
    assert "Skipping user" in capsys.readouterr().out


def test_bad_cv_arg(synth_roots):
    rc = deam_classifier.main(["-cv", "abc", "-m", "gnb", "--device", "cpu",
                               "--models-root", synth_roots["models"],
                               "--deam-root", synth_roots["deam"],
                               "--amg-root", synth_roots["amg"]])
    assert rc == 2


def test_generic_model_workflow(synth_roots):
    """Pre-train a non-committee registry model (rf) and run AL with it —
    its pickles must load and stay frozen through AL iterations."""
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    assert deam_classifier.main(["-cv", "2", "-m", "rf"] + flags) == 0
    assert deam_classifier.main(["-cv", "2", "-m", "gnb"] + flags) == 0
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "1"] + flags)
    assert rc == 0


def test_missing_pretrained_dir_is_clean_error(synth_roots, capsys):
    """AL before pre-training exits with a message, not a traceback
    (reference parity: amg_test.py:81-84)."""
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--models-root", synth_roots["models"],
                        "--deam-root", synth_roots["deam"],
                        "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 1
    assert "No pre-trained models" in capsys.readouterr().out


def test_cnn_jax_pretrain_cli(synth_roots, tmp_path, rng):
    """The cnn_jax registry path end to end through the CLI: npy audio ->
    device store -> fold training -> msgpack artifact + TensorBoard."""
    import glob

    pytest.importorskip("torch.utils.tensorboard")

    npy = os.path.join(synth_roots["deam"], "npy")
    os.makedirs(npy, exist_ok=True)
    for sid in range(1, 25):
        np.save(os.path.join(npy, f"{sid}.npy"),
                (rng.standard_normal(1600) * 0.05).astype(np.float32))
    tiny = ('{"n_channels": 4, "n_fft": 64, "hop_length": 32, "n_mels": 16,'
            ' "n_layers": 2, "input_length": 1024}')
    rc = deam_classifier.main(
        ["-cv", "1", "-m", "cnn_jax", "--epochs", "2",
         "--cnn-config-json", tiny, "--tb-dir", str(tmp_path / "tb"),
         "--models-root", synth_roots["models"],
         "--deam-root", synth_roots["deam"],
         "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 0
    pre = os.path.join(synth_roots["models"], "pretrained")
    assert glob.glob(os.path.join(pre, "classifier_cnn.it_0.msgpack"))
    assert glob.glob(str(tmp_path / "tb" / "fold_0" / "events.out.*"))


def test_mesh_auto_cli(synth_roots, capsys):
    """--mesh auto routes the production AL path through the pool-sharded
    scorers (8 virtual devices under the test harness)."""
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    assert deam_classifier.main(["-cv", "2", "-m", "gnb"] + flags) == 0
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "1", "--mesh", "auto",
                        "--pad-pool-to", "64"] + flags)
    assert rc == 0
    out = capsys.readouterr().out
    assert "Scoring mesh: 8 device(s)" in out
    assert "final mean F1" in out


def test_distributed_flag_joins_before_mesh(synth_roots, capsys, monkeypatch):
    """--distributed plumbs to multihost.initialize BEFORE backend use and
    --mesh auto then takes the global (all-hosts) pool mesh; single-process
    semantics are identical, so the full AL workflow runs through it."""
    from consensus_entropy_tpu.parallel import multihost

    calls = []
    monkeypatch.setattr(
        multihost, "initialize",
        lambda coord=None, n=None, pid=None: calls.append((coord, n, pid)))
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    assert deam_classifier.main(["-cv", "2", "-m", "gnb"] + flags) == 0
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "1", "--mesh", "auto",
                        "--distributed", "head:1234,1,0"] + flags)
    assert rc == 0
    assert calls == [("head:1234", 1, 0)]
    out = capsys.readouterr().out
    assert "across 1 host(s)" in out


def test_distributed_flag_rejects_bad_spec(synth_roots, capsys):
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--distributed", "nonsense",
                        "--models-root", synth_roots["models"],
                        "--deam-root", synth_roots["deam"],
                        "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 1
    assert "COORD,N,ID" in capsys.readouterr().out


def test_distributed_rejects_numeric_mesh(synth_roots, capsys):
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--distributed", "head:1234,2,0", "--mesh", "4",
                        "--models-root", synth_roots["models"],
                        "--deam-root", synth_roots["deam"],
                        "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 1
    assert "requires --mesh auto" in capsys.readouterr().out


def test_distributed_requires_mesh_flag(synth_roots, capsys):
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--distributed", "head:1234,2,0",
                        "--models-root", synth_roots["models"],
                        "--deam-root", synth_roots["deam"],
                        "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 1
    assert "requires --mesh auto" in capsys.readouterr().out
