"""CLI integration: pre-train on a synthetic DEAM, personalize on a synthetic
AMG1608 — the reference's two-command workflow end to end (README.md:43-60
of the reference), host-only committee."""

import json
import os

import numpy as np
import pytest

from consensus_entropy_tpu.cli import amg_test, deam_classifier
from tests.synth_data import build_synth_roots


@pytest.fixture
def synth_roots(tmp_path, rng):
    """A miniature DEAM + AMG1608 on disk, class-separable features."""
    return build_synth_roots(tmp_path, rng)


def test_full_workflow(synth_roots, capsys):
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]

    # 1. pre-train three classic members × 2 folds each
    for model in ("gnb", "sgd", "xgb"):
        assert deam_classifier.main(["-cv", "2", "-m", model] + flags) == 0
    pre = os.path.join(synth_roots["models"], "pretrained")
    pkls = [f for f in os.listdir(pre) if f.endswith(".pkl")]
    assert len(pkls) == 6  # 3 algos × 2 folds
    out = capsys.readouterr().out
    assert "CV RESULTS" in out and "F1" in out

    # 2. AL personalization, mc mode, 2 users
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "2"] + flags)
    assert rc == 0
    users_dir = os.path.join(synth_roots["models"], "users")
    users = sorted(os.listdir(users_dir))
    assert len(users) == 2
    udir = os.path.join(users_dir, users[0], "mc")
    assert os.path.exists(os.path.join(udir, "DONE"))
    metrics = [json.loads(l) for l in open(os.path.join(udir,
                                                        "metrics.jsonl"))]
    assert len(metrics) == 3  # epoch0 + 2 AL iterations
    assert len(metrics[-1]["f1"]) == 6  # every committee member evaluated
    # committee was persisted back
    assert any(f.endswith(".pkl") for f in os.listdir(udir))

    # 3. resume: second invocation skips completed users
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "2"] + flags)
    assert rc == 0
    assert "Skipping user" in capsys.readouterr().out


def test_bad_cv_arg(synth_roots):
    rc = deam_classifier.main(["-cv", "abc", "-m", "gnb", "--device", "cpu",
                               "--models-root", synth_roots["models"],
                               "--deam-root", synth_roots["deam"],
                               "--amg-root", synth_roots["amg"]])
    assert rc == 2


def test_generic_model_workflow(synth_roots):
    """Pre-train a non-committee registry model (rf) and run AL with it —
    its pickles must load and stay frozen through AL iterations."""
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    assert deam_classifier.main(["-cv", "2", "-m", "rf"] + flags) == 0
    assert deam_classifier.main(["-cv", "2", "-m", "gnb"] + flags) == 0
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "1"] + flags)
    assert rc == 0


def test_missing_pretrained_dir_is_clean_error(synth_roots, capsys):
    """AL before pre-training exits with a message, not a traceback
    (reference parity: amg_test.py:81-84)."""
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--models-root", synth_roots["models"],
                        "--deam-root", synth_roots["deam"],
                        "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 1
    assert "No pre-trained models" in capsys.readouterr().out


def test_cnn_jax_pretrain_cli(synth_roots, tmp_path, rng):
    """The cnn_jax registry path end to end through the CLI: npy audio ->
    device store -> fold training -> msgpack artifact + TensorBoard."""
    import glob

    pytest.importorskip("torch.utils.tensorboard")

    npy = os.path.join(synth_roots["deam"], "npy")
    os.makedirs(npy, exist_ok=True)
    for sid in range(1, 25):
        np.save(os.path.join(npy, f"{sid}.npy"),
                (rng.standard_normal(1600) * 0.05).astype(np.float32))
    tiny = ('{"n_channels": 4, "n_fft": 64, "hop_length": 32, "n_mels": 16,'
            ' "n_layers": 2, "input_length": 1024}')
    rc = deam_classifier.main(
        ["-cv", "1", "-m", "cnn_jax", "--epochs", "2",
         "--cnn-config-json", tiny, "--tb-dir", str(tmp_path / "tb"),
         "--models-root", synth_roots["models"],
         "--deam-root", synth_roots["deam"],
         "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 0
    pre = os.path.join(synth_roots["models"], "pretrained")
    assert glob.glob(os.path.join(pre, "classifier_cnn.it_0.msgpack"))
    assert glob.glob(str(tmp_path / "tb" / "fold_0" / "events.out.*"))


def test_mesh_auto_cli(synth_roots, capsys):
    """--mesh auto routes the production AL path through the pool-sharded
    scorers (8 virtual devices under the test harness)."""
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    assert deam_classifier.main(["-cv", "2", "-m", "gnb"] + flags) == 0
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "1", "--mesh", "auto",
                        "--pad-pool-to", "64"] + flags)
    assert rc == 0
    out = capsys.readouterr().out
    assert "Scoring mesh: 8 device(s)" in out
    assert "final mean F1" in out


def test_mesh_auto_cnn_committee_cli(synth_roots, tmp_path, rng, capsys):
    """CNN committee through the AL CLI with --mesh auto: the CLI derives
    BOTH the pool scoring mesh and the (dp=1, member) training mesh, and
    the member-sharded retrain runs inside the production loop."""
    import glob

    tiny = ('{"n_channels": 4, "n_fft": 64, "hop_length": 32, "n_mels": 16,'
            ' "n_layers": 2, "input_length": 1024}')
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    for root, ids in ((synth_roots["deam"], range(1, 25)),
                      (synth_roots["amg"], range(201, 241))):
        npy = os.path.join(root, "npy")
        os.makedirs(npy, exist_ok=True)
        for sid in ids:
            np.save(os.path.join(npy, f"{sid}.npy"),
                    (rng.standard_normal(1600) * 0.05).astype(np.float32))
    rc = deam_classifier.main(["-cv", "1", "-m", "cnn_jax", "--epochs", "1",
                               "--cnn-config-json", tiny] + flags)
    assert rc == 0
    rc = amg_test.main(["-q", "3", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "1", "--mesh", "auto",
                        "--retrain-epochs", "1",
                        "--cnn-config-json", tiny] + flags)
    assert rc == 0
    out = capsys.readouterr().out
    assert "Scoring mesh: 8 device(s)" in out
    assert "Training mesh: 8 device(s) on the member axis" in out
    assert "final mean F1" in out
    users = glob.glob(os.path.join(synth_roots["models"], "users", "*",
                                   "mc", "DONE"))
    assert users


def test_distributed_flag_joins_before_mesh(synth_roots, capsys, monkeypatch):
    """--distributed plumbs to multihost.initialize BEFORE backend use and
    --mesh auto then takes the global (all-hosts) pool mesh; single-process
    semantics are identical, so the full AL workflow runs through it."""
    from consensus_entropy_tpu.parallel import multihost

    calls = []
    monkeypatch.setattr(
        multihost, "initialize",
        lambda coord=None, n=None, pid=None: calls.append((coord, n, pid)))
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    assert deam_classifier.main(["-cv", "2", "-m", "gnb"] + flags) == 0
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--max-users", "1", "--mesh", "auto",
                        "--distributed", "head:1234,1,0"] + flags)
    assert rc == 0
    assert calls == [("head:1234", 1, 0)]
    out = capsys.readouterr().out
    assert "across 1 host(s)" in out


def test_distributed_flag_rejects_bad_spec(synth_roots, capsys):
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--distributed", "nonsense",
                        "--models-root", synth_roots["models"],
                        "--deam-root", synth_roots["deam"],
                        "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 1
    assert "COORD,N,ID" in capsys.readouterr().out


def test_distributed_rejects_numeric_mesh(synth_roots, capsys):
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--distributed", "head:1234,2,0", "--mesh", "4",
                        "--models-root", synth_roots["models"],
                        "--deam-root", synth_roots["deam"],
                        "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 1
    assert "requires --mesh auto" in capsys.readouterr().out


def test_distributed_requires_mesh_flag(synth_roots, capsys):
    rc = amg_test.main(["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
                        "--distributed", "head:1234,2,0",
                        "--models-root", synth_roots["models"],
                        "--deam-root", synth_roots["deam"],
                        "--amg-root", synth_roots["amg"], "--device", "cpu"])
    assert rc == 1
    assert "requires --mesh auto" in capsys.readouterr().out


@pytest.mark.slow
@pytest.mark.fleet
def test_fleet_cli_matches_sequential(synth_roots, capsys):
    """``--fleet N`` end to end: identical per-user workspaces/metrics to
    the sequential CLI (same pretrained committee, same seeds), plus the
    cohort-level fleet_metrics.jsonl; a rerun skips completed users."""
    import shutil

    flags = ["--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    seq_mr = os.path.join(synth_roots["models"], "seq")
    fleet_mr = os.path.join(synth_roots["models"], "fleet")
    for model in ("gnb", "sgd"):
        assert deam_classifier.main(
            ["-cv", "2", "-m", model, "--models-root", seq_mr] + flags) == 0
    shutil.copytree(os.path.join(seq_mr, "pretrained"),
                    os.path.join(fleet_mr, "pretrained"))
    al = ["-q", "4", "-e", "2", "-m", "mc", "-n", "10", "--max-users", "3"]
    assert amg_test.main(al + ["--models-root", seq_mr] + flags) == 0
    assert amg_test.main(al + ["--fleet", "2", "--models-root", fleet_mr]
                         + flags) == 0
    out = capsys.readouterr().out
    assert "Fleet cohort of 2 users" in out and "fleet summary:" in out
    seq_users = os.path.join(seq_mr, "users")
    fleet_users = os.path.join(fleet_mr, "users")
    uids = sorted(os.listdir(seq_users))
    assert sorted(f for f in os.listdir(fleet_users)
                  if f not in ("fleet_metrics.jsonl", "spans.jsonl")) \
        == uids
    for uid in uids:
        sd = os.path.join(seq_users, uid, "mc")
        fd = os.path.join(fleet_users, uid, "mc")
        assert os.path.exists(os.path.join(fd, "DONE"))
        seq_recs = [json.loads(l)
                    for l in open(os.path.join(sd, "metrics.jsonl"))]
        fleet_recs = [json.loads(l)
                      for l in open(os.path.join(fd, "metrics.jsonl"))]
        assert fleet_recs == seq_recs
    events = [json.loads(l) for l in
              open(os.path.join(fleet_users, "fleet_metrics.jsonl"))]
    assert sum(e["event"] == "user_done" for e in events) == len(uids)
    assert events[-1]["event"] == "fleet_summary"
    # rerun skips every completed user
    assert amg_test.main(al + ["--fleet", "2", "--models-root", fleet_mr]
                         + flags) == 0
    assert "Skipping user" in capsys.readouterr().out


def test_fleet_rejects_mesh_and_distributed(synth_roots, capsys):
    base = ["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
            "--models-root", synth_roots["models"],
            "--deam-root", synth_roots["deam"],
            "--amg-root", synth_roots["amg"], "--device", "cpu"]
    # an explicit width composes (pool-axis mesh serving); the 'auto'
    # spelling stays sequential-only — rejected with the pointer to N
    assert amg_test.main(base + ["--fleet", "2", "--mesh", "auto"]) == 1
    assert "sequential path's spelling" in capsys.readouterr().out
    assert amg_test.main(base + ["--fleet", "0"]) == 1
    assert ">= 1" in capsys.readouterr().out


def test_serve_flag_validation(synth_roots, capsys):
    base = ["-q", "4", "-e", "2", "-m", "mc", "-n", "10",
            "--models-root", synth_roots["models"],
            "--deam-root", synth_roots["deam"],
            "--amg-root", synth_roots["amg"], "--device", "cpu"]
    assert amg_test.main(base + ["--serve", "2", "--fleet", "2"]) == 1
    assert "exclusive" in capsys.readouterr().out
    assert amg_test.main(base + ["--serve", "0"]) == 1
    assert ">= 1" in capsys.readouterr().out
    # --serve composes with an explicit mesh width (pool-axis mesh
    # serving); only the 'auto' spelling is rejected
    assert amg_test.main(base + ["--serve", "2", "--mesh", "auto"]) == 1
    assert "sequential path's spelling" in capsys.readouterr().out
    assert amg_test.main(base + ["--serve", "2", "--pad-pool-to", "64"]) == 1
    assert "--bucket-widths" in capsys.readouterr().out
    assert amg_test.main(base + ["--serve", "2",
                                 "--bucket-widths", "64,abc"]) == 1
    assert "comma-separated" in capsys.readouterr().out
    assert amg_test.main(base + ["--bucket-widths", "64"]) == 1
    assert "requires --serve" in capsys.readouterr().out
    assert amg_test.main(base + ["--admit-window-ms", "10"]) == 1
    assert "requires --serve" in capsys.readouterr().out
    # the fault-domain flags are serve-only too
    for flags in (["--watchdog-s", "5"], ["--failure-budget", "2"],
                  ["--breaker-threshold", "3"], ["--no-serve-journal"],
                  ["--breaker-cooldown-s", "1"]):
        assert amg_test.main(base + flags) == 1
        assert "requires --serve" in capsys.readouterr().out
    assert amg_test.main(base + ["--serve", "2",
                                 "--failure-budget", "0"]) == 1
    assert ">= 1" in capsys.readouterr().out
    # fabric + compaction + probe-budget flags are serve-only too
    for flags in (["--hosts", "2"], ["--lease-s", "2"],
                  ["--breaker-probes", "1"], ["--journal-compact-kb", "64"]):
        assert amg_test.main(base + flags) == 1
        assert "requires --serve" in capsys.readouterr().out
    assert amg_test.main(base + ["--serve", "2", "--hosts", "0"]) == 1
    assert ">= 1" in capsys.readouterr().out
    assert amg_test.main(base + ["--serve", "2", "--hosts", "2",
                                 "--no-serve-journal"]) == 1
    assert "source of truth" in capsys.readouterr().out
    assert amg_test.main(base + ["--serve", "2",
                                 "--fabric-worker", "h0"]) == 1
    assert "internal" in capsys.readouterr().out


@pytest.mark.slow
@pytest.mark.serve
def test_serve_cli_matches_sequential(synth_roots, capsys):
    """``--serve N`` end to end: identical per-user workspaces/metrics to
    the sequential CLI (same pretrained committee, same seeds), admission
    telemetry in fleet_metrics.jsonl; a rerun skips completed users."""
    import shutil

    flags = ["--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    seq_mr = os.path.join(synth_roots["models"], "seq")
    serve_mr = os.path.join(synth_roots["models"], "serve")
    for model in ("gnb", "sgd"):
        assert deam_classifier.main(
            ["-cv", "2", "-m", model, "--models-root", seq_mr] + flags) == 0
    shutil.copytree(os.path.join(seq_mr, "pretrained"),
                    os.path.join(serve_mr, "pretrained"))
    al = ["-q", "4", "-e", "2", "-m", "mc", "-n", "10", "--max-users", "3"]
    assert amg_test.main(al + ["--models-root", seq_mr] + flags) == 0
    assert amg_test.main(al + ["--serve", "2", "--bucket-widths", "32,64",
                               "--models-root", serve_mr] + flags) == 0
    out = capsys.readouterr().out
    assert "serve summary:" in out
    seq_users = os.path.join(seq_mr, "users")
    serve_users = os.path.join(serve_mr, "users")
    uids = sorted(os.listdir(seq_users))
    serve_files = {"fleet_metrics.jsonl", "serve_journal.jsonl",
                   "serve_poison.jsonl", "spans.jsonl"}
    assert sorted(f for f in os.listdir(serve_users)
                  if f not in serve_files
                  and not f.endswith((".lock", ".ckpt"))) == uids
    # the admission journal shows every user enqueued/admitted/finished
    jrecs = [json.loads(l) for l in
             open(os.path.join(serve_users, "serve_journal.jsonl"))]
    assert {r["user"] for r in jrecs if r["event"] == "finish"} \
        == {u for u in uids}
    for uid in uids:
        sd = os.path.join(seq_users, uid, "mc")
        fd = os.path.join(serve_users, uid, "mc")
        assert os.path.exists(os.path.join(fd, "DONE"))
        seq_recs = [json.loads(l)
                    for l in open(os.path.join(sd, "metrics.jsonl"))]
        serve_recs = [json.loads(l)
                      for l in open(os.path.join(fd, "metrics.jsonl"))]
        assert serve_recs == seq_recs
    events = [json.loads(l) for l in
              open(os.path.join(serve_users, "fleet_metrics.jsonl"))]
    assert sum(e["event"] == "admit" for e in events) == len(uids)
    assert sum(e["event"] == "user_done" for e in events) == len(uids)
    assert events[-1]["event"] == "fleet_summary"
    # rerun skips every completed user
    assert amg_test.main(al + ["--serve", "2", "--bucket-widths", "32,64",
                               "--models-root", serve_mr] + flags) == 0
    assert "Skipping user" in capsys.readouterr().out


@pytest.mark.slow
@pytest.mark.serve
def test_fabric_cli_matches_sequential(synth_roots, capsys):
    """``--serve 2 --hosts 2`` end to end: the coordinator re-execs this
    CLI as two worker processes over the shared synthetic tree; per-user
    workspaces/metrics are identical to the sequential CLI, the journal
    records leases + per-host admits, and a rerun resolves instantly
    (everyone finished, no workers spawned)."""
    import shutil

    flags = ["--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    seq_mr = os.path.join(synth_roots["models"], "seqf")
    fab_mr = os.path.join(synth_roots["models"], "fabric")
    for model in ("gnb", "sgd"):
        assert deam_classifier.main(
            ["-cv", "2", "-m", model, "--models-root", seq_mr] + flags) == 0
    shutil.copytree(os.path.join(seq_mr, "pretrained"),
                    os.path.join(fab_mr, "pretrained"))
    al = ["-q", "4", "-e", "2", "-m", "mc", "-n", "10", "--max-users", "3"]
    assert amg_test.main(al + ["--models-root", seq_mr] + flags) == 0
    fab = al + ["--serve", "2", "--hosts", "2", "--lease-s", "5",
                "--journal-compact-kb", "64", "--models-root", fab_mr]
    assert amg_test.main(fab + flags) == 0
    out = capsys.readouterr().out
    assert "fabric summary:" in out
    seq_users = os.path.join(seq_mr, "users")
    fab_users = os.path.join(fab_mr, "users")
    uids = sorted(os.listdir(seq_users))
    for uid in uids:
        fd = os.path.join(fab_users, uid, "mc")
        assert os.path.exists(os.path.join(fd, "DONE"))
        seq_recs = [json.loads(l) for l in open(
            os.path.join(seq_users, uid, "mc", "metrics.jsonl"))]
        fab_recs = [json.loads(l)
                    for l in open(os.path.join(fd, "metrics.jsonl"))]
        assert fab_recs == seq_recs
    from consensus_entropy_tpu.serve import AdmissionJournal

    st = AdmissionJournal(
        os.path.join(fab_users, "serve_journal.jsonl")).state
    assert st.finished == set(uids) and not st.pending
    assert set(st.hosts) == {"h0", "h1"}
    assert set(st.assigned.values()) <= {"h0", "h1"}
    # per-worker engine telemetry landed beside the shared journal
    assert os.path.exists(os.path.join(fab_users,
                                       "fleet_metrics_h0.jsonl"))
    # rerun: the journal resolves everyone up front — no workers spawned
    assert amg_test.main(fab + flags) == 0
    assert '"users": 0' in capsys.readouterr().out


@pytest.mark.slow
@pytest.mark.serve
@pytest.mark.acquire
def test_qbdc_cli_serve_hosts_matches_sequential(synth_roots, tmp_path,
                                                 rng, capsys):
    """ISSUE 6 acceptance: ``--al-mode qbdc`` runs under ``--serve N
    --hosts H`` — a CNN registry pretrained via the CLI, then a 2-host
    dropout-committee fabric whose per-user metrics are bit-identical to
    the sequential qbdc CLI over the same tree."""
    import shutil

    tiny = ('{"n_channels": 4, "n_fft": 64, "hop_length": 32, "n_mels": 16,'
            ' "n_layers": 2, "input_length": 1024}')
    flags = ["--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    for root, ids in ((synth_roots["deam"], range(1, 25)),
                      (synth_roots["amg"], range(201, 241))):
        npy = os.path.join(root, "npy")
        os.makedirs(npy, exist_ok=True)
        for sid in ids:
            np.save(os.path.join(npy, f"{sid}.npy"),
                    (rng.standard_normal(1600) * 0.05).astype(np.float32))
    seq_mr = os.path.join(synth_roots["models"], "seqq")
    fab_mr = os.path.join(synth_roots["models"], "fabq")
    assert deam_classifier.main(
        ["-cv", "1", "-m", "cnn_jax", "--epochs", "1",
         "--cnn-config-json", tiny, "--models-root", seq_mr] + flags) == 0
    shutil.copytree(os.path.join(seq_mr, "pretrained"),
                    os.path.join(fab_mr, "pretrained"))
    al = ["-q", "3", "-e", "2", "--al-mode", "qbdc", "-n", "10",
          "--qbdc-k", "6", "--retrain-epochs", "1",
          "--cnn-config-json", tiny, "--max-users", "2"]
    assert amg_test.main(al + ["--models-root", seq_mr] + flags) == 0
    fab = al + ["--serve", "2", "--hosts", "2", "--lease-s", "10",
                "--models-root", fab_mr]
    assert amg_test.main(fab + flags) == 0
    out = capsys.readouterr().out
    assert "fabric summary:" in out
    seq_users = os.path.join(seq_mr, "users")
    fab_users = os.path.join(fab_mr, "users")
    uids = sorted(os.listdir(seq_users))
    assert len(uids) == 2
    for uid in uids:
        fd = os.path.join(fab_users, uid, "qbdc")
        assert os.path.exists(os.path.join(fd, "DONE"))
        seq_recs = [json.loads(l) for l in open(
            os.path.join(seq_users, uid, "qbdc", "metrics.jsonl"))]
        fab_recs = [json.loads(l)
                    for l in open(os.path.join(fd, "metrics.jsonl"))]
        assert fab_recs == seq_recs
    from consensus_entropy_tpu.serve import AdmissionJournal

    st = AdmissionJournal(
        os.path.join(fab_users, "serve_journal.jsonl")).state
    assert st.finished == set(uids) and not st.pending


def test_qbdc_cli_requires_cnn_registry(synth_roots, capsys):
    """``--al-mode qbdc`` against a host-only registry is a clean error,
    and ``--qbdc-k`` is validated."""
    flags = ["--models-root", synth_roots["models"],
             "--deam-root", synth_roots["deam"],
             "--amg-root", synth_roots["amg"], "--device", "cpu"]
    assert deam_classifier.main(["-cv", "2", "-m", "gnb"] + flags) == 0
    base = ["-q", "3", "-e", "1", "-m", "qbdc", "-n", "10"]
    assert amg_test.main(base + ["--qbdc-k", "0"] + flags) == 1
    assert "--qbdc-k" in capsys.readouterr().out
    assert amg_test.main(base + flags) == 1
    assert "needs pre-trained CNN members" in capsys.readouterr().out


def test_pretrain_classic_parallel_folds_match_sequential(tmp_path, rng):
    """n_jobs>1 (the reference's cross_validate(n_jobs=10) fold pool,
    deam_classifier.py:326) must produce identical metrics and artifacts
    to the sequential path — fold RNG is drawn before dispatch."""
    from consensus_entropy_tpu.train import pretrain

    n = 120
    X = rng.standard_normal((n, 6)).astype(np.float32)
    y = np.tile(np.arange(4), n // 4)
    song_ids = np.repeat(np.arange(n // 4), 4)
    seq = pretrain.pretrain_classic("gnb", X, y, song_ids, cv=3,
                                    out_dir=str(tmp_path / "a"), seed=5)
    par = pretrain.pretrain_classic("gnb", X, y, song_ids, cv=3,
                                    out_dir=str(tmp_path / "b"), seed=5,
                                    n_jobs=2)
    assert seq == par
    assert (sorted(os.listdir(tmp_path / "a"))
            == sorted(os.listdir(tmp_path / "b")))
