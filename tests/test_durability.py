"""Storage-integrity hardening (ISSUE 19): CRC framing, the
resilience.io fault seam, fencing epochs, and cetpu-fsck.

All pure host and tier-1 fast (no jax import).  The invariant under
test, end to end: a COMPLETE (newline-terminated) journal line was
durably written — if it fails its frame CRC that is bit-rot and replay
HALTS with a precise diagnosis instead of silently diverging; a line
WITHOUT its newline is the one artifact a crash can leave and is
quarantine-truncated on reopen.  The real-process versions of these
drills (byte-flip under a live fabric, the double-coordinator fencing
drill) run in ``scripts/fsck_check.sh`` / ``scripts/fault_matrix.sh``.
"""

from __future__ import annotations

import errno
import json
import os

import pytest

from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience import io as dio
from consensus_entropy_tpu.resilience.faults import (
    FaultRule,
    InjectedKill,
)
from consensus_entropy_tpu.serve.hosts import EpochGate
from consensus_entropy_tpu.serve.journal import (
    AdmissionJournal,
    JournalCorruption,
    JsonlTail,
    _AppendFsyncFile,
    validate_journal_file,
)

pytestmark = [pytest.mark.serve, pytest.mark.faults]


# -- frame format ------------------------------------------------------------


def test_frame_roundtrip_and_header():
    rec = {"event": "admit", "seq": 3, "user": "u1"}
    line = dio.frame_record(rec)
    assert line.startswith(b"w1 ") and line.endswith(b"\n")
    status, out = dio.parse_frame(line)
    assert status == "ok" and out == rec
    status, hdr = dio.parse_frame(dio.frame_header())
    assert status == "ok" and dio.is_header(hdr)
    assert hdr == {"wal": dio.WAL_VERSION}
    assert not dio.is_header(rec)


def test_legacy_line_parses_as_legacy():
    status, rec = dio.parse_frame(b'{"event": "admit", "seq": 1}\n')
    assert status == "legacy" and rec["event"] == "admit"


def test_every_single_byte_flip_is_detected():
    """The acceptance criterion verbatim: a byte flipped ANYWHERE in a
    framed record (magic, CRC hex, payload) is detected — no flip
    yields a silently different parsed record."""
    line = dio.frame_record({"event": "finish", "seq": 9, "user": "u"})
    for i in range(len(line) - 1):  # final newline: framing, not data
        flipped = bytearray(line)
        flipped[i] ^= 0x01
        status, _rec = dio.parse_frame(bytes(flipped))
        assert status == "corrupt", f"flip at byte {i} undetected"


# -- replay: legacy compatibility, corruption halt, torn tail ----------------


def _raw_lines(path):
    with open(path, "rb") as f:
        return f.read().split(b"\n")


def test_legacy_journal_still_loads_and_new_appends_are_framed(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    with open(jp, "wb") as f:  # a pre-framing (v1) journal
        f.write(b'{"event": "enqueue", "seq": 1, "user": "a"}\n'
                b'{"event": "admit", "seq": 2, "user": "a"}\n')
    j = AdmissionJournal(jp)
    assert j.state.last == {"a": "admit"}
    j.append("finish", "a")
    j.close()
    lines = _raw_lines(jp)
    assert lines[0].startswith(b"{")       # legacy lines untouched
    assert lines[2].startswith(b"w1 ")     # new append framed
    assert AdmissionJournal(jp).state.finished == {"a"}


def test_corrupt_midfile_record_halts_replay_with_diagnosis(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        for i in range(4):
            j.append("enqueue", f"u{i}")
    lines = _raw_lines(jp)
    bad = bytearray(lines[2])
    bad[len(bad) // 2] ^= 0xFF
    lines[2] = bytes(bad)
    with open(jp, "wb") as f:
        f.write(b"\n".join(lines))
    with pytest.raises(JournalCorruption) as ei:
        AdmissionJournal(jp)
    # the diagnosis names file, line and byte offset — the fsck handoff
    assert jp in str(ei.value) and ":3" in str(ei.value)
    assert "cetpu-fsck" in str(ei.value)


def test_torn_tail_quarantined_and_truncated_on_reopen(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        j.append("enqueue", "a")
        j.append("admit", "a")
    durable = open(jp, "rb").read()
    with open(jp, "ab") as f:
        f.write(b"w1 deadbeef {\"event\": \"fini")  # no newline: torn
    j2 = AdmissionJournal(jp)
    assert j2.state.last == {"a": "admit"}  # torn bytes never replayed
    # the writer's first append repairs: torn bytes quarantined, file
    # truncated back to its durable tail, then the new record lands
    j2.append("finish", "a")
    j2.close()
    qpath = dio.quarantine_path(jp)
    assert os.path.exists(qpath)
    qrec = json.loads(open(qpath, "rb").read().split(b"\n")[0])
    assert qrec["reason"] == "torn tail"
    repaired = open(jp, "rb").read()
    assert repaired[:len(durable)] == durable  # durable prefix intact
    status, last = dio.parse_frame(repaired[len(durable):])
    assert status == "ok" and last["event"] == "finish"  # clean splice
    assert AdmissionJournal(jp).state.finished == {"a"}
    assert validate_journal_file(jp) == []


def test_complete_corrupt_line_is_never_torn_tail(tmp_path):
    """A newline-TERMINATED garbage line is bit-rot, not a crash
    artifact: reopen halts instead of quietly quarantining, because a
    durably-written record vanished."""
    jp = str(tmp_path / "j.jsonl")
    with AdmissionJournal(jp) as j:
        j.append("enqueue", "a")
    with open(jp, "ab") as f:
        f.write(b"w1 deadbeef {\"event\": \"fini\n")  # terminated!
    with pytest.raises(JournalCorruption):
        AdmissionJournal(jp)


# -- the io fault seam -------------------------------------------------------


def test_io_write_enospc_and_eio_raise_before_any_byte(tmp_path):
    p = str(tmp_path / "w.bin")
    for point, eno in (("io.write.enospc", errno.ENOSPC),
                       ("io.write.eio", errno.EIO)):
        with faults.inject(FaultRule(point, "raise")) as inj:
            with open(p, "wb") as f:
                with pytest.raises(OSError) as ei:
                    dio.write(f, b"payload", path=p)
            assert ei.value.errno == eno and inj.fired
        assert os.path.getsize(p) == 0  # nothing reached the file


def test_io_write_short_leaves_half_the_payload(tmp_path):
    p = str(tmp_path / "w.bin")
    with faults.inject(FaultRule("io.write.short", "kill")):
        with open(p, "wb") as f:
            with pytest.raises(InjectedKill):
                dio.write(f, b"0123456789", path=p)
    assert open(p, "rb").read() == b"01234"  # half, flushed, then died


def test_io_fsync_drop_is_silent(tmp_path):
    p = str(tmp_path / "w.bin")
    seen = []
    listener = lambda kind, path: seen.append(kind)  # noqa: E731
    dio.add_listener(listener)
    try:
        with faults.inject(FaultRule("io.fsync", "raise")) as inj:
            with open(p, "wb") as f:
                f.write(b"x")
                dio.fsync(f, path=p, member="wal")  # no exception
            assert inj.fired
    finally:
        dio.remove_listener(listener)
    assert "io.fsync" in seen  # dropped silently but SURFACED


def test_io_rename_fault_fails_the_commit_and_cleans_tmp(tmp_path):
    p = str(tmp_path / "a.json")
    with faults.inject(FaultRule("io.rename", "raise")):
        with pytest.raises(OSError):
            dio.atomic_write(p, b"data", member="lease")
    assert not os.path.exists(p)
    assert not os.path.exists(p + ".tmp")  # OSError path cleans up


def test_member_filter_targets_one_writer(tmp_path):
    """``member=compact`` fault rules must not fire on WAL appends."""
    p = str(tmp_path / "w.bin")
    rule = FaultRule("io.write.eio", "raise", member="compact")
    with faults.inject(rule) as inj:
        with open(p, "ab") as f:
            dio.write(f, b"fine", path=p, member="wal")
        assert not inj.fired


# -- crash drills through the journal ---------------------------------------


def test_short_write_kill_then_replay_is_bit_identical(tmp_path):
    """The short-write-then-SIGKILL kill-matrix row, in-process: die
    mid-append, reopen, and the journal replays exactly the pre-kill
    state with the torn half-line quarantined."""
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp)
    j.append("enqueue", "a")
    j.append("admit", "a")
    pre = j.state.to_dict()
    with faults.inject(FaultRule("io.write.short", "kill")):
        with pytest.raises(InjectedKill):
            j.append("finish", "a")  # dies with half a line on disk
    j.close()  # what the kernel does to the dead process's flock
    j2 = AdmissionJournal(jp)
    post = j2.state.to_dict()
    assert post == pre  # bit-identical replay: the append never happened
    j2.append("finish", "a")  # the retried transition lands cleanly
    assert os.path.exists(dio.quarantine_path(jp))
    j2.close()
    assert AdmissionJournal(jp).state.finished == {"a"}


def test_enospc_during_compaction_leaks_no_tmp_and_retries(tmp_path):
    """The satellite fix: auto-compaction hitting ENOSPC must not kill
    the append (the record is already durable), must not leak a .tmp
    sibling, and the next compaction succeeds once space returns."""
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp, compact_bytes=300)
    with faults.inject(FaultRule("io.write.enospc", "raise",
                                 member="compact", times=1)) as inj:
        for i in range(12):  # enough appends to cross compact_bytes
            j.append("enqueue", f"u{i}")
        assert inj.fired
    assert not os.path.exists(jp + ".tmp")
    assert not os.path.exists(jp + ".ckpt.tmp")
    n0 = j.state.seq
    for i in range(12, 30):
        j.append("enqueue", f"u{i}")  # triggers a successful compaction
    assert j.compactions >= 1
    j.close()
    st = AdmissionJournal(jp).state
    assert st.seq == n0 + 18 and len(st.queued) == 30


def test_kill_mid_compaction_sweeps_tmp_on_reopen(tmp_path):
    """Dying between the checkpoint tmp write and its rename leaves a
    ``.tmp`` stray; the next open sweeps it and replays the intact WAL."""
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp, compact_bytes=300)
    with faults.inject(FaultRule("io.rename", "kill", member="compact")):
        with pytest.raises(InjectedKill):
            for i in range(12):
                j.append("enqueue", f"u{i}")
    j.close()  # what the kernel does to the dead process's flock
    leftovers = [n for n in os.listdir(str(tmp_path))
                 if n.endswith(".tmp")]
    assert leftovers  # the kill left the stray...
    j2 = AdmissionJournal(jp)
    assert not [n for n in os.listdir(str(tmp_path))
                if n.endswith(".tmp")]  # ...and reopen swept it
    # every append BEFORE the compaction kill is durable and replayed
    # (the triggering append's record lands before compaction runs)
    survived = len(j2.state.queued)
    assert 0 < survived < 12
    assert j2.state.queued == [f"u{i}" for i in range(survived)]
    for i in range(survived, 12):
        j2.append("enqueue", f"u{i}")  # the rerun finishes the intake
    j2.close()
    assert len(AdmissionJournal(jp).state.queued) == 12
    assert validate_journal_file(jp) == []


def test_jsonl_tail_skips_and_counts_corrupt_lines(tmp_path):
    """The coordinator's reader half: a corrupt line in another
    process's WAL is counted + quarantined (sidecar), never delivered,
    and the cursor moves past it."""
    p = str(tmp_path / "events.jsonl")
    w = _AppendFsyncFile(p)
    w.append({"event": "admit", "seq": 1, "user": "a"})
    w.append({"event": "finish", "seq": 2, "user": "a"})
    w.append({"event": "admit", "seq": 3, "user": "b"})
    w.close()
    lines = _raw_lines(p)
    bad = bytearray(lines[2])
    bad[-3] ^= 0xFF
    lines[2] = bytes(bad)
    with open(p, "wb") as f:
        f.write(b"\n".join(lines))
    tail = JsonlTail(p)
    got = [rec["seq"] for rec, _off in tail.poll()]
    assert got == [1, 3]
    assert tail.corrupt == 1
    assert os.path.exists(dio.quarantine_path(p))


# -- fencing epochs ----------------------------------------------------------


def test_epoch_gate_latches_highest_and_fences_stale():
    g = EpochGate()
    assert g.admit({"user": "a"})            # legacy line: no ep field
    assert g.epoch is None
    assert g.admit({"user": "a", "ep": 2})
    assert g.epoch == 2
    assert not g.admit({"user": "b", "ep": 1})   # stale incarnation
    assert g.admit({"user": "c", "ep": 2})       # same incarnation
    assert g.admit({"user": "d", "ep": 5})       # successor takes over
    assert not g.admit({"user": "e", "ep": 2})   # old one now stale too
    assert g.epoch == 5 and g.fenced == 2


def test_epoch_feed_stamps_every_line(tmp_path):
    from consensus_entropy_tpu.serve.fabric import _EpochFeed

    p = str(tmp_path / "assign.jsonl")
    feed = _EpochFeed(_AppendFsyncFile(p), 3)
    feed.append({"user": "a"})
    feed.append({"drain": True})
    feed.close()
    recs = [rec for rec, _off in JsonlTail(p).poll()]
    assert [r.get("ep") for r in recs] == [3, 3]
    assert recs[0]["user"] == "a" and recs[1]["drain"] is True


def test_journal_coordinator_epoch_is_monotonic(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp)
    assert j.state.coordinator_epoch == 0
    j.append("epoch", epoch=1)
    j.append("epoch_fenced", "u1", epoch=0)  # audit record: no effect
    j.append("epoch", epoch=3)
    assert j.state.coordinator_epoch == 3
    # a replayed stale claim can never move the epoch backwards
    j.append("epoch", epoch=2)
    assert j.state.coordinator_epoch == 3
    j.close()
    st = AdmissionJournal(jp).state
    assert st.coordinator_epoch == 3
    # and the snapshot round-trip preserves it (compaction path)
    from consensus_entropy_tpu.serve.journal import JournalState

    assert JournalState.from_dict(st.to_dict()).coordinator_epoch == 3
    assert validate_journal_file(jp) == []


def test_successive_coordinators_claim_increasing_epochs(tmp_path):
    """Split-brain seed: each incarnation over the SAME journal claims
    strictly higher — the stale one's stamped lines are rejectable."""
    from consensus_entropy_tpu.serve.fabric import (
        FabricConfig,
        FabricCoordinator,
    )

    jp = str(tmp_path / "j.jsonl")
    epochs = []
    for _ in range(3):
        j = AdmissionJournal(jp)
        coord = FabricCoordinator(j, str(tmp_path),
                                  FabricConfig(hosts=1))
        epochs.append(coord.epoch)
        j.append("epoch", epoch=coord.epoch)  # what run() journals
        j.close()
    assert epochs == [1, 2, 3]


def test_server_ack_epoch_fields():
    from consensus_entropy_tpu.serve.server import FleetServer

    ack = FleetServer.ack_epoch
    srv = type("S", (), {"epoch": None})()
    assert ack(srv) == {}
    srv.epoch = 4
    assert ack(srv) == {"ep": 4}


# -- cetpu-fsck --------------------------------------------------------------


def _build_users_dir(tmp_path) -> tuple[str, dict]:
    d = str(tmp_path / "users")
    os.makedirs(d)
    jp = os.path.join(d, "serve_journal.jsonl")
    with AdmissionJournal(jp) as j:
        for i in range(5):
            j.append("enqueue", f"u{i}")
            j.append("admit", f"u{i}")
        j.append("finish", "u0")
        state = j.state.to_dict()
    return d, state


def _flip_byte(path: str, line_no: int):
    with open(path, "rb") as f:
        lines = f.read().split(b"\n")
    bad = bytearray(lines[line_no])
    bad[len(bad) // 2] ^= 0xFF
    lines[line_no] = bytes(bad)
    with open(path, "wb") as f:
        f.write(b"\n".join(lines))


def test_fsck_detects_repairs_and_replays_to_parity(tmp_path, capsys):
    from consensus_entropy_tpu.cli.fsck import main as fsck_main

    d, pre = _build_users_dir(tmp_path)
    jp = os.path.join(d, "serve_journal.jsonl")
    _flip_byte(jp, 3)  # an enqueue record: disposition-neutral damage
    open(jp + ".tmp", "wb").close()  # a killed compaction's stray
    assert fsck_main([d]) == 1                   # detect, exit nonzero
    assert "corrupt" in capsys.readouterr().out
    assert fsck_main([d, "--repair"]) == 0       # repair + re-verify
    assert fsck_main([d]) == 0                   # now clean
    assert not os.path.exists(jp + ".tmp")
    assert os.path.exists(dio.quarantine_path(jp))
    # replay parity: only the quarantined line's own record is gone;
    # every disposition the journal committed is intact
    st = AdmissionJournal(jp).state
    assert st.finished == {"u0"}
    assert st.last["u4"] == "admit" and st.seq == pre["seq"]


def test_fsck_refuses_a_live_wal(tmp_path):
    from consensus_entropy_tpu.cli.fsck import main as fsck_main

    d, _ = _build_users_dir(tmp_path)
    jp = os.path.join(d, "serve_journal.jsonl")
    j = AdmissionJournal(jp)
    j.append("enqueue", "live")  # the first append takes the flock
    _flip_byte(jp, 2)            # bit-rot lands while the writer is live
    try:
        assert fsck_main([d, "--repair"]) == 2
        assert os.path.exists(jp)  # untouched: never racily rewritten
    finally:
        j.close()


def test_fsck_verifies_checkpoint_containers(tmp_path):
    """Corrupt CETPU1 containers are detected (and never 'repaired' —
    there is no redundancy; recovery rolls back a generation)."""
    import struct
    import zlib

    from consensus_entropy_tpu.cli.fsck import main as fsck_main

    d, _ = _build_users_dir(tmp_path)
    payload = b"\x01" * 64
    meta = json.dumps({"crc32": zlib.crc32(payload)}).encode()
    ck = os.path.join(d, "member.msgpack")
    with open(ck, "wb") as f:
        f.write(b"CETPU1\n" + struct.pack("<I", len(meta)) + meta
                + payload)
    assert fsck_main([d]) == 0  # intact container passes
    with open(ck, "r+b") as f:
        f.seek(-20, os.SEEK_END)
        f.write(b"\xff")
    assert fsck_main([d]) == 1
    assert fsck_main([d, "--repair"]) == 1  # unrepairable by design
