"""Fleet batched scoring vs the single-user production fns.

The contract the fleet engine rests on: every row of a vmapped
``make_fleet_scoring_fns`` result is BIT-IDENTICAL to the jitted
single-user fn from ``make_scoring_fns`` on that user's inputs — for all
four acquisition modes, including quarantine member masks and padded pool
rows.  (Equality is against the jitted single-user fns — the production
path ``Acquirer.run_scoring`` calls — not the unjitted python functions,
whose fusion can differ by 1 ulp.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_entropy_tpu.ops import scoring

pytestmark = pytest.mark.fleet


def _probs(rng, u, m, n, c=4):
    p = rng.uniform(0.01, 1.0, size=(u, m, n, c)).astype(np.float32)
    return p / p.sum(axis=-1, keepdims=True)


def _masks(rng, u, n, n_live):
    """Per-user pool masks with padded tail rows plus a few random
    quarantine-style holes mid-pool."""
    mask = np.zeros((u, n), bool)
    mask[:, :n_live] = True
    for i in range(u):
        holes = rng.choice(n_live, size=3, replace=False)
        mask[i, holes] = False
    return mask


def _assert_rows_equal(batched, single, i, mask_row):
    """Bit-for-bit row equality: full values/indices, entropies on live
    rows (padding rows are -inf on both sides; compare them too via
    array_equal, which treats equal infs as equal)."""
    np.testing.assert_array_equal(np.asarray(batched.values[i]),
                                  np.asarray(single.values))
    np.testing.assert_array_equal(np.asarray(batched.indices[i]),
                                  np.asarray(single.indices))
    np.testing.assert_array_equal(np.asarray(batched.entropy[i]),
                                  np.asarray(single.entropy))


def test_fleet_mc_matches_single(rng):
    u, m, n, k = 4, 5, 96, 6
    p = _probs(rng, u, m, n)
    mask = _masks(rng, u, n, 80)
    fleet = scoring.make_fleet_scoring_fns(k=k)
    single = scoring.make_scoring_fns(k=k)
    res = fleet["mc"](p, mask)
    for i in range(u):
        _assert_rows_equal(res, single["mc"](p[i], mask[i]), i, mask[i])


def test_fleet_mc_member_mask_matches_single(rng):
    """Quarantine masks: a per-user (U, M) member mask batched must equal
    the single-user masked call — fixed-M cohorts with quarantined
    members ride the ``*_masked`` variants."""
    u, m, n, k = 3, 6, 64, 5
    p = _probs(rng, u, m, n)
    mask = _masks(rng, u, n, 60)
    mmask = np.ones((u, m), bool)
    mmask[0, 2] = False
    mmask[2, 0] = mmask[2, 5] = False
    fleet = scoring.make_fleet_scoring_fns(k=k)

    def one(pp, pm, mm):
        return scoring.score_mc(pp, pm, k=k, member_mask=mm,
                                tie_break="fast")

    single = jax.jit(one)
    res = fleet["mc_masked"](p, mask, mmask)
    for i in range(u):
        _assert_rows_equal(res, single(p[i], mask[i], mmask[i]), i, mask[i])


def test_fleet_hc_matches_single(rng):
    u, n, k = 4, 80, 7
    counts = rng.integers(1, 30, size=(u, n, 4))
    freq = np.round(counts / counts.sum(-1, keepdims=True),
                    3).astype(np.float32)
    freq[:, 70:] = 0.0  # padded rows (all-zero, behind the mask)
    mask = _masks(rng, u, n, 70)
    fleet = scoring.make_fleet_scoring_fns(k=k)
    single = scoring.make_scoring_fns(k=k)
    res = fleet["hc"](freq, mask)
    for i in range(u):
        _assert_rows_equal(res, single["hc"](freq[i], mask[i]), i, mask[i])
    # the production hc path: precomputed row entropies + masked top-k
    from consensus_entropy_tpu.ops.entropy import shannon_entropy

    ent = jax.jit(jax.vmap(shannon_entropy))(freq)
    res_pre = fleet["hc_pre"](ent, mask)
    for i in range(u):
        s = single["hc_pre"](np.asarray(ent[i]), mask[i])
        _assert_rows_equal(res_pre, s, i, mask[i])


def test_fleet_mix_matches_single(rng):
    u, m, n, k = 3, 4, 72, 6
    p = _probs(rng, u, m, n)
    pool_mask = _masks(rng, u, n, 64)
    counts = rng.integers(1, 25, size=(u, n, 4))
    hc = np.round(counts / counts.sum(-1, keepdims=True),
                  3).astype(np.float32)
    hc_mask = pool_mask.copy()
    hc_mask[:, 40:] = False  # hc rows already queried in earlier iterations
    fleet = scoring.make_fleet_scoring_fns(k=k)
    single = scoring.make_scoring_fns(k=k)
    res = fleet["mix"](p, pool_mask, hc, hc_mask)
    for i in range(u):
        s = single["mix"](p[i], pool_mask[i], hc[i], hc_mask[i])
        _assert_rows_equal(res, s, i, pool_mask[i])

    mmask = np.ones((u, m), bool)
    mmask[1, 3] = False

    def one(pp, pm, hf, hm, mm):
        return scoring.score_mix(pp, pm, hf, hm, k=k, member_mask=mm,
                                 tie_break="fast")

    jone = jax.jit(one)
    res_m = fleet["mix_masked"](p, pool_mask, hc, hc_mask, mmask)
    for i in range(u):
        s = jone(p[i], pool_mask[i], hc[i], hc_mask[i], mmask[i])
        _assert_rows_equal(res_m, s, i, pool_mask[i])


def test_fleet_rand_matches_single(rng):
    """rand relies on partitionable threefry: a batched key array's
    per-user draws equal each key's own draws regardless of batching."""
    u, n, k = 4, 56, 5
    mask = _masks(rng, u, n, 48)
    keys = [jax.random.key(100 + i) for i in range(u)]
    batched_keys = scoring.stack_user_keys(keys)
    assert scoring.is_key_array(batched_keys)
    assert not scoring.is_key_array(jnp.zeros(3))
    assert not scoring.is_key_array(mask)
    fleet = scoring.make_fleet_scoring_fns(k=k)
    single = scoring.make_scoring_fns(k=k)
    res = fleet["rand"](batched_keys, mask)
    for i in range(u):
        _assert_rows_equal(res, single["rand"](keys[i], mask[i]), i, mask[i])


def test_fleet_fns_cached_per_k():
    a = scoring.make_fleet_scoring_fns(k=5)
    b = scoring.make_fleet_scoring_fns(k=5, tie_break="fast")
    c = scoring.make_fleet_scoring_fns(k=6)
    assert a is b and a is not c  # same normalization as make_scoring_fns
