"""First-party GBDT: C++/numpy backend parity, boosting quality, and the
exact continued-boosting contract of the reference's patched xgboost
(``/root/reference/xgboost/sklearn.py:854-860`` — classes/objective pinned
across warm starts on class-deficient batches, ``amg_test.py:507``)."""

import numpy as np
import pytest

from consensus_entropy_tpu import native
from consensus_entropy_tpu.config import NUM_CLASSES
from consensus_entropy_tpu.models.gbdt import (
    GBDT,
    NativeGBDTMember,
    QuantileBinner,
)


def _clusters(rng, n=300, f=10):
    X = rng.standard_normal((n, f))
    centers = rng.standard_normal((NUM_CLASSES, f)) * 3
    y = rng.integers(0, NUM_CLASSES, size=n)
    X += centers[y]
    return X.astype(np.float32), y


# -- binner ----------------------------------------------------------------

def test_binner_monotone_and_bounded(rng):
    X = rng.standard_normal((500, 6)).astype(np.float32)
    b = QuantileBinner(64).fit(X)
    codes = b.transform(X)
    assert codes.dtype == np.uint8 and codes.max() < 64
    # monotone per feature: sorting raw values sorts the codes
    j = 3
    order = np.argsort(X[:, j], kind="stable")
    assert (np.diff(codes[order, j].astype(int)) >= 0).all()


def test_binner_constant_feature(rng):
    X = np.hstack([np.full((50, 1), 7.0), rng.standard_normal((50, 1))])
    codes = QuantileBinner(16).fit(X).transform(X)
    assert (codes[:, 0] == codes[0, 0]).all()


def test_binner_rejects_unfitted_and_wrong_width(rng):
    b = QuantileBinner(8)
    with pytest.raises(RuntimeError):
        b.transform(np.zeros((3, 2)))
    b.fit(np.zeros((10, 2)))
    with pytest.raises(ValueError):
        b.transform(np.zeros((3, 5)))


# -- tree build: both backends produce identical trees ---------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_build_tree_native_numpy_identical(seed):
    rng = np.random.default_rng(seed)
    n, f, n_bins = 400, 8, 32
    Xb = rng.integers(0, n_bins, size=(n, f)).astype(np.uint8)
    g = rng.standard_normal(n).astype(np.float32)
    h = rng.uniform(0.1, 1.0, n).astype(np.float32)
    kw = dict(max_depth=4, n_bins=n_bins, lam=1.0,
              min_child_weight=1.0, min_gain=0.0)
    f_np, t_np, v_np = native._gbdt_build_tree_np(Xb, g, h, **{
        "max_depth": 4, "n_bins": n_bins, "lam": 1.0,
        "min_child_weight": 1.0, "min_gain": 0.0})
    if native.backend() == "numpy":
        pytest.skip("no native toolchain: single backend only")
    f_c, t_c, v_c = native.gbdt_build_tree(Xb, g, h, **kw)
    np.testing.assert_array_equal(f_c, f_np)
    np.testing.assert_array_equal(t_c, t_np)
    np.testing.assert_allclose(v_c, v_np, rtol=1e-12, atol=1e-12)


def test_build_tree_fits_gradients(rng):
    """A depth-2 tree on a 1-feature step function recovers the step."""
    n = 200
    Xb = np.zeros((n, 1), np.uint8)
    Xb[n // 2:, 0] = 10
    g = np.where(np.arange(n) < n // 2, 1.0, -1.0).astype(np.float32)
    h = np.ones(n, np.float32)
    feat, thr, val = native.gbdt_build_tree(
        Xb, g, h, max_depth=2, n_bins=16, lam=0.0)
    assert feat[0] == 0  # root splits on the only feature
    m = native.gbdt_predict_margins(Xb, feat[None], thr[None], val[None],
                                    np.zeros(1, np.int32), 1, 1.0)
    np.testing.assert_allclose(m[:, 0], -g, atol=1e-12)  # Newton step −g/h


def test_min_child_weight_blocks_tiny_splits():
    Xb = np.zeros((10, 1), np.uint8)
    Xb[0, 0] = 5  # a 1-row split candidate
    g = np.r_[5.0, np.zeros(9)].astype(np.float32)
    h = np.ones(10, np.float32)
    feat, _, val = native.gbdt_build_tree(
        Xb, g, h, max_depth=3, n_bins=16, lam=1.0, min_child_weight=2.0)
    assert feat[0] == -1  # forced leaf: the only useful split is 1-vs-9...
    assert val[0] != 0.0


def test_native_wrappers_reject_corrupt_inputs(rng):
    """The C++ core indexes by bin code / tree class; the wrappers must
    reject violating input loudly on BOTH backends (the native path would
    otherwise write out of bounds)."""
    Xb = np.full((5, 2), 40, np.uint8)
    g = np.zeros(5, np.float32)
    h = np.ones(5, np.float32)
    with pytest.raises(ValueError, match="bin codes"):
        native.gbdt_build_tree(Xb, g, h, max_depth=2, n_bins=32)
    with pytest.raises(ValueError, match="max_depth"):
        native.gbdt_build_tree(Xb, g, h, max_depth=-1, n_bins=64)
    feat = np.full((1, 7), -1, np.int32)
    thr = np.zeros((1, 7), np.int32)
    val = np.zeros((1, 7), np.float64)
    with pytest.raises(ValueError, match="tree_class"):
        native.gbdt_predict_margins(Xb, feat, thr, val,
                                    np.array([4], np.int32), 4, 0.3)


def test_predict_margins_empty_forest(rng):
    model = GBDT(NUM_CLASSES)
    Xb = rng.integers(0, 4, size=(7, 3)).astype(np.uint8)
    np.testing.assert_array_equal(model.margins(Xb),
                                  np.zeros((7, NUM_CLASSES)))
    p = model.predict_proba(Xb)
    np.testing.assert_allclose(p, 0.25, atol=1e-7)


# -- boosting quality -------------------------------------------------------

def test_gbdt_learns_separable_clusters(rng):
    X, y = _clusters(rng)
    m = NativeGBDTMember(n_estimators=20, max_depth=3)
    m.fit(X, y)
    assert (m.predict(X) == y).mean() > 0.95
    p = m.predict_proba(X)
    assert p.shape == (len(X), NUM_CLASSES)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)


def test_gbdt_quality_tracks_sklearn(rng):
    """Held-out accuracy within a few points of sklearn's GBDT on the same
    clustered task (different algorithm details — histogram bins, diagonal
    softmax hessian — so parity is statistical, not numerical)."""
    from sklearn.ensemble import GradientBoostingClassifier

    X, y = _clusters(rng, n=600)
    Xtr, ytr, Xte, yte = X[:400], y[:400], X[400:], y[400:]
    ours = NativeGBDTMember(n_estimators=30, max_depth=3).fit(Xtr, ytr)
    ref = GradientBoostingClassifier(n_estimators=30, max_depth=3,
                                     random_state=0).fit(Xtr, ytr)
    acc_ours = (ours.predict(Xte) == yte).mean()
    acc_ref = (ref.predict(Xte) == yte).mean()
    assert acc_ours >= acc_ref - 0.05, (acc_ours, acc_ref)


def test_refit_retrains_from_scratch(rng):
    """fit() on an already-fitted member must equal a fresh fit (stale trees
    under replaced bin edges would otherwise be scored on mismatched
    codes)."""
    X1, y1 = _clusters(rng)
    X2, y2 = _clusters(rng)
    X2 *= 5.0  # very different quantile edges
    m = NativeGBDTMember(n_estimators=5)
    m.fit(X1, y1)
    m.fit(X2, y2)
    fresh = NativeGBDTMember(n_estimators=5).fit(X2, y2)
    assert m.model.n_trees == fresh.model.n_trees
    np.testing.assert_array_equal(m.predict_proba(X2[:15]),
                                  fresh.predict_proba(X2[:15]))


def test_predict_margins_rejects_mismatched_shapes(rng):
    Xb = rng.integers(0, 8, size=(5, 2)).astype(np.uint8)
    feat = np.full((2, 7), -1, np.int32)
    thr = np.zeros((2, 7), np.int32)
    val = np.zeros((2, 7), np.float64)
    tc = np.zeros(2, np.int32)
    with pytest.raises(ValueError, match="disagree"):
        native.gbdt_predict_margins(Xb, feat, thr[:1], val, tc, 4, 0.3)
    with pytest.raises(ValueError, match="margins"):
        native.gbdt_predict_margins(Xb, feat, thr, val, tc, 4, 0.3,
                                    margins=np.zeros((5, 3)))


def test_gbdt_deterministic(rng):
    X, y = _clusters(rng)
    a = NativeGBDTMember(n_estimators=5).fit(X, y).predict_proba(X[:20])
    b = NativeGBDTMember(n_estimators=5).fit(X, y).predict_proba(X[:20])
    np.testing.assert_array_equal(a, b)


# -- continued boosting: the reference-patch semantics, exactly -------------

def test_update_is_true_continued_boosting(rng):
    """update(X, y) must equal boosting the same rounds on that batch in one
    model whose forest already holds the pre-training trees — i.e. margins
    continue, nothing is refit, no padding rows are injected."""
    X, y = _clusters(rng)
    Xq, yq = X[y == 1][:10], y[y == 1][:10]  # single-class AL batch

    m = NativeGBDTMember(n_estimators=8, update_estimators=4)
    m.fit(X, y)
    trees_before = m.model.n_trees
    m.update(Xq, yq)
    assert m.model.n_trees == trees_before + 4 * NUM_CLASSES

    # replay: same pre-train, then boost the query batch directly
    m2 = NativeGBDTMember(n_estimators=8, update_estimators=4)
    m2.fit(X, y)
    m2.model.boost(m2.binner.transform(Xq), yq, 4)
    np.testing.assert_array_equal(m.predict_proba(X[:25]),
                                  m2.predict_proba(X[:25]))


def test_update_objective_stays_four_class(rng):
    """Repeated single-class updates drift toward that class but every class
    keeps probability mass (the pinned K-class softmax objective)."""
    X, y = _clusters(rng)
    m = NativeGBDTMember(n_estimators=10, update_estimators=5)
    m.fit(X, y)
    sel = y == 3
    p_before = m.predict_proba(X[sel][:20])
    for _ in range(3):
        m.update(X[sel][:10], y[sel][:10])
    p_after = m.predict_proba(X[sel][:20])
    assert p_after[:, 3].mean() > p_before[:, 3].mean()
    assert (p_after > 0).all() and p_after.shape[1] == NUM_CLASSES


def test_fit_requires_all_classes(rng):
    X, y = _clusters(rng)
    m = NativeGBDTMember(n_estimators=2)
    with pytest.raises(ValueError, match="all 4 classes"):
        m.fit(X[y != 2], y[y != 2])


def test_update_rejects_out_of_range_labels(rng):
    """Negative labels must raise, not wrap to the last class via numpy
    indexing (siblings in the boosted slot raise on unseen labels too)."""
    X, y = _clusters(rng)
    m = NativeGBDTMember(n_estimators=2).fit(X, y)
    with pytest.raises(ValueError, match="labels"):
        m.update(X[:4], np.full(4, -1))
    with pytest.raises(ValueError, match="labels"):
        m.update(X[:4], np.full(4, NUM_CLASSES))


def test_member_roundtrip_preserves_binner_and_forest(rng, tmp_path):
    X, y = _clusters(rng)
    m = NativeGBDTMember(n_estimators=6, update_estimators=3).fit(X, y)
    path = str(tmp_path / "classifier_xgb.it_0.pkl")
    m.save(path)
    m2 = NativeGBDTMember.load(path)
    np.testing.assert_array_equal(m.predict_proba(X[:12]),
                                  m2.predict_proba(X[:12]))
    m2.update(X[y == 0][:5], y[y == 0][:5])  # still boostable after load
    assert m2.model.n_trees == m.model.n_trees + 3 * NUM_CLASSES


def test_workspace_dispatches_native_gbdt(rng, tmp_path):
    """load_committee routes the boosted slot to NativeGBDTMember via the
    pickle's fmt tag (three coexisting formats: xgboost raw, sklearn
    fallback, native)."""
    from consensus_entropy_tpu.al.workspace import load_committee
    from consensus_entropy_tpu.models.sklearn_members import GNBMember

    X, y = _clusters(rng)
    NativeGBDTMember("it_0", n_estimators=4).fit(X, y).save(
        str(tmp_path / "classifier_xgb.it_0.pkl"))
    GNBMember("it_0").fit(X, y).save(
        str(tmp_path / "classifier_gnb.it_0.pkl"))
    committee = load_committee(str(tmp_path))
    by_kind = {m.kind: m for m in committee.host_members}
    assert isinstance(by_kind["xgb"], NativeGBDTMember)
    committee.update_host(X[:4], y[:4])  # boosted slot updates in committee
