"""cetpu-lint (ISSUE 12): rule fixtures, suppression/baseline semantics,
the model↔runtime registry cross-check, and the repo-lints-clean gate.

Pure host and tier-1 fast: every fixture is a `lint_source` call over a
snippet at a VIRTUAL repo path (so the path-scoped rules see the right
scope without touching the tree), plus one full-tree integration lint.
"""

from __future__ import annotations

import json
import os
import textwrap

from consensus_entropy_tpu.analysis import (
    ProjectModel,
    available_rules,
    lint_paths,
    lint_source,
)
from consensus_entropy_tpu.analysis.cli import (
    DEFAULT_PATHS,
    main as lint_main,
)
from consensus_entropy_tpu.analysis.engine import (
    apply_baseline,
    baseline_from,
    load_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = ProjectModel.from_repo(REPO)

PKG_FILE = "consensus_entropy_tpu/ops/fixture.py"
REPLAY_FILE = "consensus_entropy_tpu/serve/fixture.py"


def rules_fired(src: str, path: str = PKG_FILE, *, model=MODEL,
                select=None) -> list[str]:
    src = textwrap.dedent(src)
    return [f.rule for f in lint_source(src, path, model=model,
                                        select=select)]


# -- the model loader vs the runtime registries ------------------------------


def test_model_matches_runtime_registries():
    """The satellite cross-check: the statically parsed tables EQUAL the
    runtime objects, so fault-point / event-schema / donation checks can
    never drift from what the code actually enforces."""
    from consensus_entropy_tpu.obs import export
    from consensus_entropy_tpu.ops import scoring
    from consensus_entropy_tpu.resilience import faults

    assert MODEL.fault_points == faults.FAULT_POINTS
    # the v2.1 table carries per-field KINDS — pinned dict-equal so the
    # lint model's type checks can never drift from the runtime
    # validator's (obs.export.validate_metrics)
    assert MODEL.event_fields == {k: dict(v) for k, v
                                  in export.EVENT_FIELDS.items()}
    assert all(kind in export.FIELD_KINDS
               for fields in MODEL.event_fields.values()
               for kind in fields.values())
    assert MODEL.fused_donate == {k: tuple(v) for k, v
                                  in scoring.FUSED_DONATE.items()}


def test_registry_has_the_contracted_rules():
    rules = available_rules()
    assert len(rules) >= 6
    for name in ("donation-after-use", "prng-literal-key",
                 "prng-key-reuse", "replay-wallclock",
                 "replay-unseeded-rng", "replay-set-iteration",
                 "implicit-host-sync", "fault-point-literal",
                 "event-schema", "lock-discipline", "raw-durable-io"):
        assert name in rules, name


# -- rule 1: donation-after-use ---------------------------------------------


def test_donation_after_use_fires_on_read_of_donated_buffer():
    fired = rules_fired("""
        def step(fns, probs, mask):
            res = fns["mc_fused"](probs, mask)
            return res, mask.sum()
    """)
    assert fired == ["donation-after-use"]


def test_donation_after_use_silent_when_result_adopted():
    fired = rules_fired("""
        def step(fns, probs, mask):
            res = fns["mc_fused"](probs, mask)
            mask = res.pool_mask
            return res, mask.sum()
    """)
    assert fired == []


def test_donation_tracks_local_jax_jit_donate_argnums():
    src = """
        import jax

        _scatter = jax.jit(_impl, donate_argnums=0)

        def stage(buf, rows, p):
            out = _scatter(buf, rows, p)
            return buf
    """
    assert rules_fired(src) == ["donation-after-use"]
    # the repo's own idiom — rebind the donated path to the result —
    # is clean even through an attribute chain
    assert rules_fired("""
        import jax

        _scatter = jax.jit(_impl, donate_argnums=0)

        def stage(self, rows, p):
            self.device.probs = _scatter(self.device.probs, rows, p)
            return self.device.probs
    """) == []


def test_donation_flows_through_local_aliases():
    """Flow-sensitive rebind tracking: a pure alias assignment links the
    names, so donating through EITHER spelling spends both."""
    # donate the alias, read the original
    assert rules_fired("""
        def step(fns, probs, mask):
            m = mask
            res = fns["mc_fused"](probs, m)
            return res, mask.sum()
    """) == ["donation-after-use"]
    # donate the original, read the alias
    assert rules_fired("""
        def step(fns, probs, mask):
            m = mask
            res = fns["mc_fused"](probs, mask)
            return res, m.sum()
    """) == ["donation-after-use"]
    # aliases chase attribute chains too (the persistent-buffer idiom)
    assert rules_fired("""
        import jax

        _scatter = jax.jit(_impl, donate_argnums=0)

        def stage(self, rows, p):
            buf = self.device.probs
            self.device.probs = _scatter(buf, rows, p)
            return buf
    """) == ["donation-after-use"]


def test_donation_alias_rebind_is_clean_and_carries_consumption():
    """Rebinding breaks exactly ONE link: the rebound name is fresh,
    while a surviving alias still holds the spent buffer."""
    # the repo idiom through an alias: rebind it to the returned buffer
    assert rules_fired("""
        def step(fns, probs, mask):
            m = mask
            res = fns["mc_fused"](probs, m)
            m = res.pool_mask
            return res, m.sum()
    """) == []
    # rebinding the alias TARGET does not launder the alias: m still
    # references the donated buffer after mask moves on
    assert rules_fired("""
        def step(fns, probs, mask):
            m = mask
            res = fns["mc_fused"](probs, mask)
            mask = res.pool_mask
            return res, m.sum()
    """) == ["donation-after-use"]
    # ... and the rebound target itself reads clean
    assert rules_fired("""
        def step(fns, probs, mask):
            m = mask
            res = fns["mc_fused"](probs, mask)
            mask = res.pool_mask
            return res, mask.sum()
    """) == []


# -- rule 2a: prng-literal-key ----------------------------------------------


def test_prng_literal_key_fires_in_library_code_only():
    src = """
        import jax

        key = jax.random.key(0)
    """
    assert rules_fired(src) == ["prng-literal-key"]
    assert rules_fired(src.replace("key(0)", "PRNGKey(42)")) \
        == ["prng-literal-key"]
    # tests and bench are exempt by scope
    assert rules_fired(src, "tests/test_fixture.py") == []
    # a seed-derived key is the sanctioned form
    assert rules_fired("""
        import jax

        def make(seed):
            return jax.random.key(seed)
    """) == []


# -- rule 2b: prng-key-reuse -------------------------------------------------


def test_prng_key_reuse_fires_on_two_sinks_one_key():
    fired = rules_fired("""
        import jax

        def draw(key):
            a = jax.random.uniform(key, (3,))
            b = jax.random.normal(key, (3,))
            return a, b
    """)
    assert fired == ["prng-key-reuse"]


def test_prng_key_reuse_silent_with_split_between():
    assert rules_fired("""
        import jax

        def draw(key):
            key, sub = jax.random.split(key)
            a = jax.random.uniform(sub, (3,))
            key, sub = jax.random.split(key)
            b = jax.random.normal(sub, (3,))
            return a, b
    """) == []


def test_prng_key_reuse_branches_and_loops():
    # either-or branches each consume once: clean
    assert rules_fired("""
        import jax

        def draw(key, flip):
            if flip:
                return jax.random.uniform(key, (3,))
            return jax.random.normal(key, (3,))
    """) == []
    # loop-carried reuse: the same key every iteration
    assert rules_fired("""
        import jax

        def draw(key, n):
            out = []
            for i in range(n):
                out.append(jax.random.uniform(key, (3,)))
            return out
    """) == ["prng-key-reuse"]
    # fold_in per iteration is the sanctioned loop form
    assert rules_fired("""
        import jax

        def draw(key, n):
            out = []
            for i in range(n):
                out.append(jax.random.uniform(
                    jax.random.fold_in(key, i), (3,)))
            return out
    """) == []


# -- rule 3a: replay-wallclock -----------------------------------------------


def test_replay_wallclock_scoped_to_replay_modules():
    src = """
        import time

        def stamp():
            return time.time()
    """
    assert rules_fired(src, REPLAY_FILE) == ["replay-wallclock"]
    # ops/ is not replay-critical: silent
    assert rules_fired(src, PKG_FILE) == []


def test_replay_wallclock_allows_injected_clock_seam():
    assert rules_fired("""
        import time

        class Watchdog:
            def __init__(self, deadline_s, *, clock=time.monotonic):
                self.clock = clock

            def expired(self, armed_t):
                return self.clock() - armed_t
    """, REPLAY_FILE) == []


def test_replay_wallclock_flags_call_in_default_and_bare_datetime():
    # a CALL in a parameter default is a timestamp frozen at import —
    # the opposite of a seam — and must flag
    assert rules_fired("""
        import time

        def f(t=time.time()):
            return t
    """, REPLAY_FILE) == ["replay-wallclock"]
    # the `from datetime import datetime` spelling is covered too
    assert rules_fired("""
        from datetime import datetime

        def stamp():
            return datetime.now()
    """, REPLAY_FILE) == ["replay-wallclock"]


# -- rule 3b: replay-unseeded-rng --------------------------------------------


def test_replay_unseeded_rng():
    assert rules_fired("import random\n", REPLAY_FILE) \
        == ["replay-unseeded-rng"]
    assert rules_fired("""
        import numpy as np

        def jitter():
            return np.random.default_rng().uniform()
    """, REPLAY_FILE) == ["replay-unseeded-rng"]
    assert rules_fired("""
        import numpy as np

        def jitter():
            return np.random.rand()
    """, REPLAY_FILE) == ["replay-unseeded-rng"]
    # the seeded instance is the sanctioned form
    assert rules_fired("""
        import numpy as np

        def jitter(seed):
            return np.random.default_rng(seed).uniform()
    """, REPLAY_FILE) == []


# -- rule 3c: replay-set-iteration -------------------------------------------


def test_replay_set_iteration_fires_on_order_dependent_walks():
    assert rules_fired("""
        def emit_all(xs, emit):
            for x in set(xs):
                emit(x)
    """, REPLAY_FILE) == ["replay-set-iteration"]
    assert rules_fired("""
        class Server:
            def __init__(self):
                self.pending = set()

            def collect(self):
                return [x for x in self.pending]
    """, REPLAY_FILE) == ["replay-set-iteration"]
    assert rules_fired("""
        def snapshot(live):
            return list({u for u in live})
    """, REPLAY_FILE) == ["replay-set-iteration"]


def test_replay_set_iteration_allows_order_free_consumers():
    assert rules_fired("""
        class Server:
            def __init__(self):
                self.pending = set()

            def collect(self):
                return sorted(self.pending)

            def depth(self, width):
                return sum(1 for x in self.pending if x == width)
    """, REPLAY_FILE) == []
    # a function-local `edges = set()` must not taint the same NAME in
    # other functions (the planner regression)
    assert rules_fired("""
        def derive():
            edges = set()
            edges.add(1)
            return tuple(sorted(edges))

        def restore(edges):
            return tuple(int(e) for e in edges)
    """, REPLAY_FILE) == []


# -- rule 4: implicit-host-sync ----------------------------------------------


def test_implicit_host_sync_scoped_to_hot_functions():
    sched = "consensus_entropy_tpu/fleet/scheduler.py"
    src = """
        import numpy as np

        class S:
            def _stacked_call(self, fn, vals):
                out = fn(vals)
                return float(out[0]), np.asarray(out[1]), out[2].item()

            def summary(self, out):
                return float(out[0])
    """
    fired = rules_fired(src, sched)
    # the hot function fires per sync site; the cold one is silent
    assert fired == ["implicit-host-sync"] * 3


def test_implicit_host_sync_server_scope_and_sanctioned_pull():
    """The follow-on (c) scope growth: serve/server.py dispatch paths
    and the acquirer's staging path are hot too — and the ONE sanctioned
    pull (``selection_scalars``, the 2·k selection rows) is whitelisted
    by its helper spelling, not by a noqa."""
    server = "consensus_entropy_tpu/serve/server.py"
    assert rules_fired("""
        import numpy as np

        class S:
            def _collect(self, rows):
                return np.asarray(rows[0])
    """, server) == ["implicit-host-sync"]
    acq = "consensus_entropy_tpu/al/acquisition.py"
    assert rules_fired("""
        from consensus_entropy_tpu.ops import scoring

        class A:
            def _ids(self, res):
                idx = scoring.selection_scalars(res.indices)
                ok = scoring.selection_scalars(res.values) > 0
                return idx, ok
    """, acq) == []
    # the bare spelling is whitelisted too (builtin.py imports the name)
    assert rules_fired("""
        from consensus_entropy_tpu.ops.scoring import selection_scalars

        class A:
            def finish_select(self, res):
                return selection_scalars(res.indices)
    """, acq) == []
    # anything NOT the sanctioned helper still fires there
    assert rules_fired("""
        import numpy as np

        class A:
            def finish_select(self, res):
                return float(res.values[0])
    """, acq) == ["implicit-host-sync"]


# -- rule 5: fault-point-literal ---------------------------------------------


def test_fault_point_literal():
    assert rules_fired("""
        from consensus_entropy_tpu.resilience import faults

        def go():
            faults.fire("serve.dispatch", fn="mc", width=8)
    """) == []
    assert rules_fired("""
        from consensus_entropy_tpu.resilience import faults

        def go():
            faults.fire("serve.dipatch")
    """) == ["fault-point-literal"]
    # FaultRule construction, fault_point attributes and parse_spec
    # specs resolve statically too
    assert rules_fired("""
        rule = FaultRule(point="nope", action="kill")
    """) == ["fault-point-literal"]
    assert rules_fired("""
        class Plan:
            fault_point = "pool.score"
    """) == []
    assert rules_fired("""
        rules = parse_spec("checkpoint.write:kill@3,bogus.point:raise")
    """) == ["fault-point-literal"]


# -- rule 6: event-schema ----------------------------------------------------


def test_event_schema():
    assert rules_fired("""
        def done(report, u):
            report.event("user_done", user=str(u))
    """) == []
    assert rules_fired("""
        def admit(report, u):
            report.event("admit", user=str(u))
    """) == ["event-schema"]  # missing width/wait_s/depth/live
    assert rules_fired("""
        def admit(report, u):
            report.event("totally_new_event", user=str(u))
    """) == ["event-schema"]  # unregistered kind
    # a **splat defeats the field check but the kind is still verified
    assert rules_fired("""
        def fail(report, rec):
            report.event("user_failed", **rec)
    """) == []
    assert rules_fired("""
        def emit(writer):
            writer.emit({"event": "enqueue", "user": "u1", "depth": 3,
                         "t_s": 0.1})
    """) == []
    assert rules_fired("""
        def emit(writer):
            writer.emit({"event": "enqueue", "t_s": 0.1})
    """) == ["event-schema"]


def test_event_schema_literal_types():
    """Lint follow-on (d): a required field passed as a LITERAL must
    hold its registered kind — a literal of the wrong type fires, a
    non-literal (runtime-typed) argument stays the read-time
    validator's job."""
    assert rules_fired("""
        def done(report):
            report.event("user_done", user="u1")
    """) == []
    assert rules_fired("""
        def done(report):
            report.event("user_done", user=3)
    """) == ["event-schema"]  # user must be str
    assert rules_fired("""
        def enq(report):
            report.event("enqueue", user="u1", depth=True)
    """) == ["event-schema"]  # bool is not an int count
    assert rules_fired("""
        def edges(report):
            report.event("planner_edges", edges="32,64")
    """) == ["event-schema"]  # list kind needs a list
    assert rules_fired("""
        def edges(report, e):
            report.event("planner_edges", edges=[32, 64])
            report.event("planner_edges", edges=list(e))
    """) == []  # list literal ok; Call is runtime-typed
    assert rules_fired("""
        def emit(writer):
            writer.emit({"event": "enqueue", "user": "u1",
                         "depth": "3", "t_s": 0.1})
    """) == ["event-schema"]  # dict-form literals are checked too


# -- rule 7: lock-discipline -------------------------------------------------


def test_lock_discipline_bare_acquire_fires():
    assert rules_fired("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def go(self):
                self._lock.acquire()
                try:
                    return 1
                finally:
                    self._lock.release()
    """) == ["lock-discipline"]
    # module-level locks are tracked too
    assert rules_fired("""
        import threading

        _REG = threading.Lock()

        def go():
            _REG.acquire()
    """) == ["lock-discipline"]


def test_lock_discipline_with_form_is_clean():
    assert rules_fired("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def go(self):
                with self._lock:
                    return 1
    """) == []
    # Condition has its own wait/notify protocol: not a tracked lock
    assert rules_fired("""
        import threading

        class Q:
            def __init__(self):
                self._cond = threading.Condition()

            def go(self):
                self._cond.acquire()
    """) == []


def test_lock_discipline_nested_locks_fire():
    src = """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.RLock()

            def go(self):
                with self._a:
                    with self._b:
                        return 1
    """
    assert rules_fired(src) == ["lock-discipline"]
    # a multi-item `with a, b:` is the same nested acquisition
    assert rules_fired("""
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def go(self):
                with self._a, self._b:
                    return 1
    """) == ["lock-discipline"]
    # a non-lock inner context manager under a lock is fine
    assert rules_fired("""
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()

            def go(self, path):
                with self._a:
                    with open(path) as f:
                        return f.read()
    """) == []
    # nested defs are separate control flow: a callback that takes its
    # OWN lock later does not count as held-under the enclosing with
    assert rules_fired("""
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def go(self):
                with self._a:
                    def cb():
                        with self._b:
                            return 1
                return cb
    """) == []


# -- rule: raw-durable-io ----------------------------------------------------


def test_raw_durable_io_fires_on_write_opens_in_scope():
    """Durability-critical modules (serve/, resilience/,
    al/workspace.py) must route writes through the resilience.io seam so
    the io.* fault points and CRC framing cover them."""
    src = """
        import os

        def persist(path, data):
            with open(path + ".tmp", "wb") as f:
                f.write(data)
                os.fsync(f.fileno())
            os.replace(path + ".tmp", path)
    """
    fired = rules_fired(src, REPLAY_FILE, select=["raw-durable-io"])
    assert fired == ["raw-durable-io"] * 3  # open + fsync + replace


def test_raw_durable_io_flags_mode_kw_and_append():
    fired = rules_fired("""
        def log(path, line):
            with open(path, mode="a") as f:
                f.write(line)
    """, REPLAY_FILE, select=["raw-durable-io"])
    assert fired == ["raw-durable-io"]


def test_raw_durable_io_silent_on_reads_and_out_of_scope():
    src = """
        def load(path):
            with open(path, "rb") as f:
                return f.read()

        def surgery(path):
            with open(path, "r+b") as f:  # the fault injector's corrupt
                f.write(b"x")
    """
    assert rules_fired(src, REPLAY_FILE,
                       select=["raw-durable-io"]) == []
    # the same write-open outside the durable scope is not this rule's
    # business (ops/ writes are artifacts, not ledgers)
    assert rules_fired("""
        def dump(path, data):
            with open(path, "w") as f:
                f.write(data)
    """, PKG_FILE, select=["raw-durable-io"]) == []


def test_raw_durable_io_noqa_escape():
    fired = rules_fired("""
        def lock_sibling(path):
            return open(path + ".lock", "ab")  # cetpu: noqa[raw-durable-io] zero-byte lock sibling
    """, REPLAY_FILE, select=["raw-durable-io"])
    assert fired == []


# -- suppression + baseline semantics ----------------------------------------


def test_noqa_suppresses_named_rule_only():
    base = "import time\n\n\ndef f():\n    return time.time(){}\n"
    assert rules_fired(base.format(""), REPLAY_FILE) \
        == ["replay-wallclock"]
    assert rules_fired(
        base.format("  # cetpu: noqa[replay-wallclock] wall-stamp"),
        REPLAY_FILE) == []
    assert rules_fired(base.format("  # cetpu: noqa"), REPLAY_FILE) == []
    # a noqa for a DIFFERENT rule does not suppress
    assert rules_fired(
        base.format("  # cetpu: noqa[event-schema] wrong rule"),
        REPLAY_FILE) == ["replay-wallclock"]


def test_baseline_counts_grandfather_then_ratchet():
    src = textwrap.dedent("""
        import time

        def f():
            return time.time()

        def g():
            return time.time()
    """)
    findings = lint_source(src, REPLAY_FILE, model=MODEL)
    assert [f.rule for f in findings] == ["replay-wallclock"] * 2
    baseline = baseline_from(findings)
    assert baseline == {"replay-wallclock:" + REPLAY_FILE: 2}
    # the full baseline absorbs everything; one-less leaves the LAST
    # (highest-line) finding — the ratchet direction
    assert apply_baseline(findings, baseline) == []
    partial = {"replay-wallclock:" + REPLAY_FILE: 1}
    left = apply_baseline(findings, partial)
    assert [f.line for f in left] == [findings[1].line]


def test_baseline_file_round_trip(tmp_path):
    path = tmp_path / "lint_baseline.json"
    path.write_text(json.dumps({"replay-wallclock:x.py": 2}))
    assert load_baseline(str(path)) == {"replay-wallclock:x.py": 2}
    assert load_baseline(str(tmp_path / "missing.json")) == {}


# -- the whole-repo gate -----------------------------------------------------


def test_repo_lints_clean_with_empty_baseline():
    """The acceptance pin: the committed tree has NO unbaselined,
    un-noqa'd finding, the committed baseline is empty, and the full
    pass stays interactive (<10 s)."""
    committed = load_baseline(os.path.join(REPO, "lint_baseline.json"))
    assert committed == {}, "the baseline must stay empty: fix or noqa"
    result = lint_paths(list(DEFAULT_PATHS), root=REPO, model=MODEL)
    assert result.errors == []
    assert result.findings == [], "\n".join(str(f)
                                            for f in result.findings)
    assert result.files > 100  # the walk really covered the tree
    assert result.wall_s < 10.0


def test_cli_end_to_end(tmp_path, capsys):
    """The console entry against a synthetic repo root: violating file
    → exit 1 with a JSON finding; --write-baseline grandfathers it →
    exit 0; --list-rules prints the registry."""
    pkg = tmp_path / "consensus_entropy_tpu"
    for rel, name, payload in (
            ("resilience/faults.py", "FAULT_POINTS",
             'FAULT_POINTS = frozenset({"pool.score"})'),
            ("obs/export.py", "EVENT_FIELDS",
             'EVENT_FIELDS = {"enqueue": {"user": "str", '
             '"depth": "int"}}'),
            ("ops/scoring.py", "FUSED_DONATE",
             'FUSED_DONATE = {"mc_fused": (1,)}')):
        f = pkg / rel
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(payload + "\n")
    bad = pkg / "serve" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import time\n\n\ndef f():\n    return time.time()\n")

    rc = lint_main(["--root", str(tmp_path), "--format", "json",
                    "consensus_entropy_tpu"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["rule"] for f in payload["findings"]] \
        == ["replay-wallclock"]

    rc = lint_main(["--root", str(tmp_path), "--write-baseline",
                    "consensus_entropy_tpu"])
    assert rc == 0
    capsys.readouterr()
    rc = lint_main(["--root", str(tmp_path), "consensus_entropy_tpu"])
    assert rc == 0

    assert lint_main(["--list-rules"]) == 0
    listing = capsys.readouterr().out
    assert "donation-after-use" in listing

    # unknown rule: usage error, not a lint failure
    assert lint_main(["--root", str(tmp_path),
                      "--select", "no-such-rule"]) == 2

    # a typo'd path must FAIL (usage error), not lint 0 files and pass
    assert lint_main(["--root", str(tmp_path),
                      "consensus_entropy_tpu/srve"]) == 2

    # --write-baseline refuses while files are unparseable (a partial
    # baseline would grandfather a lie) and leaves the file untouched
    (pkg / "serve" / "torn.py").write_text("def broken(:\n")
    baseline_path = tmp_path / "lint_baseline.json"
    before = baseline_path.read_text()
    assert lint_main(["--root", str(tmp_path), "--write-baseline",
                      "consensus_entropy_tpu"]) == 2
    assert baseline_path.read_text() == before
    (pkg / "serve" / "torn.py").unlink()
