"""Resilience layer: kill-at-every-boundary, checkpoint integrity +
last-good rollback, member quarantine, transient retry, preemption.

The headline suite injects a simulated process death (``InjectedKill``) at
each named fault point in turn and asserts the resumed run reproduces the
unfaulted F1 trajectory BIT-FOR-BIT — recovery paths are exercised, not
trusted.  The fast subset (mc mode) runs in tier-1; the full
mode x boundary matrix is ``slow`` and runs via ``scripts/fault_matrix.sh``.
"""

import json
import os
import signal
import struct
import time

import numpy as np
import pytest

from consensus_entropy_tpu.al import state as al_state
from consensus_entropy_tpu.al import workspace
from consensus_entropy_tpu.al.acquisition import Acquirer, \
    _sanitize_member_rows
from consensus_entropy_tpu.al.loop import ALLoop, AsyncCheckpointer, UserData
from consensus_entropy_tpu.config import ALConfig
from consensus_entropy_tpu.models.committee import (
    Committee,
    CommitteeExhaustedError,
    FramePool,
)
from consensus_entropy_tpu.models.sklearn_members import GNBMember, SGDMember
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import (
    FaultRule,
    InjectedFault,
    InjectedKill,
    TransientFault,
)
from consensus_entropy_tpu.resilience.preemption import (
    EXIT_PREEMPTED,
    Preempted,
    PreemptionGuard,
)
from consensus_entropy_tpu.resilience.retry import retry_transient
from consensus_entropy_tpu.utils.checkpoint import (
    _MAGIC,
    CheckpointCorruptError,
    load_variables,
    save_variables,
)

pytestmark = pytest.mark.faults


def _make_user(rng, n_songs=30, frames_per_song=3, n_feat=8):
    centers = rng.standard_normal((4, n_feat)) * 3.0
    labels = {}
    X, frame_song = [], []
    for s in range(n_songs):
        c = int(rng.integers(0, 4))
        sid = f"song{s:03d}"
        labels[sid] = c
        X.append(centers[c] + rng.standard_normal((frames_per_song, n_feat)))
        frame_song += [sid] * frames_per_song
    pool = FramePool(np.concatenate(X).astype(np.float32), frame_song)
    hc = rng.uniform(0.1, 1.0, (pool.n_songs, 4)).astype(np.float32)
    hc /= hc.sum(axis=1, keepdims=True)
    return UserData("u0", pool, labels, hc_rows=hc)


def _committee(rng, data, *, extra_sgd: int = 0, min_members: int = 1):
    X = data.pool.X
    y = np.array([data.labels[s] for s in np.repeat(
        data.pool.song_ids, data.pool.counts)], np.int32)
    members = [GNBMember("gnb.it_0").fit(X, y),
               SGDMember("sgd.it_0", seed=0).fit(X, y)]
    for i in range(extra_sgd):
        members.append(SGDMember(f"sgd.extra{i}", seed=i + 1).fit(X, y))
    return Committee(members, [], min_members=min_members)


def _run(data, path, mode="mc", epochs=4, seed=11, committee=None, **kw):
    loop = ALLoop(ALConfig(queries=3, epochs=epochs, mode=mode, seed=seed))
    com = committee if committee is not None \
        else _committee(np.random.default_rng(0), data)
    return loop.run_user(com, data, str(path), seed=seed, **kw)


# -- kill-at-every-boundary ----------------------------------------------

#: fault point → per-point hit index that lands the kill mid-run for the
#: host-only committee (2 members; checkpoint.write/member.* fire per
#: member, pool.score once per scored iteration, state.save once per
#: commit, multihost.sync once at run end), and the modes where the point
#: fires at all (member.predict / pool.score only exist on mc/mix paths).
BOUNDARIES = {
    "checkpoint.write": (3, ("mc", "hc", "mix", "rand", "wmc")),
    "member.retrain": (3, ("mc", "hc", "mix", "rand", "wmc")),
    "member.predict": (3, ("mc", "mix", "wmc")),
    "pool.score": (2, ("mc", "mix", "wmc")),
    "state.save": (2, ("mc", "hc", "mix", "rand", "wmc")),
    "multihost.sync": (1, ("mc", "hc", "mix", "rand", "wmc")),
}

_MATRIX = [
    pytest.param(mode, point, at,
                 marks=() if mode == "mc" else pytest.mark.slow,
                 id=f"{mode}-{point}")
    for point, (at, modes) in sorted(BOUNDARIES.items())
    for mode in modes
]


@pytest.mark.parametrize("mode,point,at", _MATRIX)
def test_kill_at_every_boundary(tmp_path, rng, mode, point, at):
    """A run killed at the named boundary, then resumed from the
    workspace, reproduces the unfaulted run's F1 trajectory bit-for-bit
    (and the identical query sequence)."""
    data = _make_user(rng)
    base = tmp_path / "base"
    base.mkdir()
    res_base = _run(data, base, mode=mode)

    d = tmp_path / "faulted"
    d.mkdir()
    with faults.inject(FaultRule(point=point, action="kill", at=at)) as inj:
        with pytest.raises(InjectedKill):
            _run(data, d, mode=mode)
        assert inj.fired, f"{point} never fired — boundary not exercised"

    committee2 = workspace.load_committee(str(d))
    res2 = _run(data, d, mode=mode, committee=committee2)
    assert res2["trajectory"] == res_base["trajectory"]
    assert (al_state.ALState.load(str(d)).queried
            == al_state.ALState.load(str(base)).queried)


#: qbdc kill rows: the dropout committee's own boundary (the mask
#: sampler) plus the shared ones its iterations cross.  Hit indices land
#: mid-run for the 1-CNN-member committee (masks/pool.score fire once per
#: scored iteration, state.save once per commit, checkpoint.write once
#: per member msgpack per generation).
QBDC_BOUNDARIES = [("acquire.qbdc.masks", 2), ("pool.score", 2),
                   ("state.save", 2), ("checkpoint.write", 2)]


@pytest.mark.slow
@pytest.mark.parametrize("point,at", QBDC_BOUNDARIES,
                         ids=[p for p, _ in QBDC_BOUNDARIES])
def test_qbdc_kill_at_every_boundary(tmp_path, point, at):
    """The qbdc rows of the kill matrix: a dropout-committee run killed
    at the named boundary — including the mode's OWN fault point, the
    mask sampler — resumes to the unfaulted trajectory bit-for-bit (mask
    keys fold from the checkpointed PRNG stream)."""
    from tests.test_acquire import (
        TINY_CNN,
        TINY_TC,
        _cnn_committee,
        _cnn_data,
    )

    cfg = ALConfig(queries=3, epochs=3, mode="qbdc", seed=11,
                   ckpt_dtype="float32", qbdc_k=6)
    data = _cnn_data(600, "u0", n_songs=10)
    base = tmp_path / "base"
    base.mkdir()
    res_base = ALLoop(cfg, retrain_epochs=1).run_user(
        _cnn_committee(data), data, str(base), seed=11)

    d = tmp_path / "faulted"
    d.mkdir()
    with faults.inject(FaultRule(point=point, action="kill", at=at)) as inj:
        with pytest.raises(InjectedKill):
            ALLoop(cfg, retrain_epochs=1).run_user(
                _cnn_committee(data), data, str(d), seed=11)
        assert inj.fired, f"{point} never fired — boundary not exercised"

    committee2 = workspace.load_committee(str(d), TINY_CNN, TINY_TC)
    res2 = ALLoop(cfg, retrain_epochs=1).run_user(committee2, data, str(d),
                                                  seed=11)
    assert res2["trajectory"] == res_base["trajectory"]
    assert (al_state.ALState.load(str(d)).queried
            == al_state.ALState.load(str(base)).queried)


# -- checkpoint integrity + last-good rollback ---------------------------


def test_checkpoint_crc_roundtrip_and_corruption(tmp_path):
    tree = {"params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}}
    p = str(tmp_path / "v.msgpack")
    save_variables(p, tree, meta={"kind": "cnn_jax"})
    v, meta = load_variables(p)
    assert "crc32" in meta
    np.testing.assert_array_equal(v["params"]["w"], tree["params"]["w"])

    faults._corrupt_file(p)  # flip the last (payload) byte: bit-rot
    with pytest.raises(CheckpointCorruptError, match="CRC mismatch"):
        load_variables(p)


def test_legacy_checkpoint_without_crc_still_loads(tmp_path):
    from flax import serialization

    tree = {"params": {"w": np.ones((2, 2), np.float32)}}
    payload = serialization.to_bytes(tree)
    header = json.dumps({"kind": "cnn_jax"}).encode()  # no crc32 key
    p = str(tmp_path / "legacy.msgpack")
    with open(p, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<I", len(header)))
        f.write(header)
        f.write(payload)
    v, meta = load_variables(p)
    assert "crc32" not in meta
    np.testing.assert_array_equal(v["params"]["w"], tree["params"]["w"])

    truncated = str(tmp_path / "trunc.msgpack")
    with open(truncated, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<I", 1000))
        f.write(b"{}")
    with pytest.raises(CheckpointCorruptError, match="truncated"):
        load_variables(truncated)


@pytest.mark.parametrize("how", ["bit_rot", "injected"])
def test_corrupt_live_checkpoint_rolls_back_one_generation(tmp_path, rng,
                                                           how):
    """A corrupt LIVE member checkpoint rolls the workspace back to the
    retained previous generation; the replayed iteration converges to the
    unfaulted trajectory exactly."""
    data = _make_user(rng)
    base = tmp_path / "base"
    base.mkdir()
    res_base = _run(data, base, epochs=4)

    d = tmp_path / "part"
    d.mkdir()
    if how == "injected":
        # corrupt the gen-2 staging write of the first member pickle via
        # the injector (hits: gen0 1-2, gen1 3-4, gen2 5); the run itself
        # completes — bit-rot is silent until the next load
        with faults.inject(FaultRule("checkpoint.write", "corrupt", at=5)):
            _run(data, d, epochs=2)
    else:
        _run(data, d, epochs=2)
        faults._corrupt_file(
            os.path.join(str(d), "classifier_gnb.gnb.it_0.pkl"))
    assert al_state.ALState.load(str(d)).next_epoch == 2

    with pytest.warns(UserWarning, match="rolled back"):
        committee2 = workspace.load_committee(str(d))
    st = al_state.ALState.load(str(d))
    assert st.next_epoch == 1  # stepped back exactly one generation
    res2 = _run(data, d, epochs=4, committee=committee2)
    assert res2["trajectory"] == res_base["trajectory"]


def test_corruption_without_snapshot_fails_loud(tmp_path, rng):
    """No complete previous-generation snapshot → the corruption error
    propagates (never a silent mixed-generation restore)."""
    data = _make_user(rng)
    d = tmp_path / "u"
    d.mkdir()
    _run(data, d, epochs=2)
    # invalidate the snapshot the way a crash mid-promote would
    marker = os.path.join(str(d), al_state.PREV_DIR, al_state.PREV_MARKER)
    os.remove(marker)
    faults._corrupt_file(os.path.join(str(d), "classifier_gnb.gnb.it_0.pkl"))
    with pytest.raises(CheckpointCorruptError):
        workspace.load_committee(str(d))


# -- member quarantine ---------------------------------------------------


@pytest.mark.parametrize("action,reason_match", [
    ("raise", "predict failed"),
    ("corrupt", "non-finite"),
])
def test_member_quarantine_degrades_gracefully(tmp_path, rng, action,
                                               reason_match):
    """A member whose predict raises (or emits NaN rows) is quarantined;
    the run completes over the survivors and the event is recorded in the
    per-user report."""
    data = _make_user(rng)
    com = _committee(np.random.default_rng(0), data, extra_sgd=1)
    d = tmp_path / "u"
    d.mkdir()
    with faults.inject(FaultRule("member.predict", action, at=1, times=-1,
                                 member="sgd.extra0")):
        res = _run(data, d, committee=com)
    assert list(com.quarantined) == ["sgd.extra0"]
    assert reason_match in com.quarantined["sgd.extra0"]
    assert len(res["trajectory"]) == 5 and np.isfinite(res["trajectory"]).all()
    events = [json.loads(l) for l in open(os.path.join(str(d),
                                                       "metrics.jsonl"))
              if "\"event\"" in l]
    assert events and events[0]["event"] == "quarantine"
    assert events[0]["member"] == "sgd.extra0"
    assert reason_match in events[0]["reason"]


def test_retrain_failure_quarantines_member(tmp_path, rng):
    data = _make_user(rng)
    com = _committee(np.random.default_rng(0), data, extra_sgd=1)
    d = tmp_path / "u"
    d.mkdir()
    with faults.inject(FaultRule("member.retrain", "raise", at=1, times=-1,
                                 member="gnb.it_0")):
        res = _run(data, d, committee=com)
    assert list(com.quarantined) == ["gnb.it_0"]
    assert "retrain failed" in com.quarantined["gnb.it_0"]
    assert len(res["trajectory"]) == 5
    # the quarantined member's checkpoint is skipped: its live file keeps
    # the state from before the quarantine, and a reloaded committee still
    # carries all members (quarantine is per-run, not persisted)
    reloaded = workspace.load_committee(str(d))
    assert len(reloaded.host_members) == 3


def test_committee_exhaustion_aborts(tmp_path, rng):
    data = _make_user(rng)
    com = _committee(np.random.default_rng(0), data, min_members=2)
    d = tmp_path / "u"
    d.mkdir()
    with faults.inject(FaultRule("member.retrain", "raise", at=1, times=-1,
                                 member="gnb.it_0")):
        with pytest.raises(CommitteeExhaustedError, match="min_members=2"):
            _run(data, d, committee=com)


def test_quarantined_rows_match_survivor_consensus(rng):
    """Acceptance: a quarantined member's rows are masked out of the
    consensus-entropy reduction and the mean renormalizes over survivors —
    selections equal a committee that never had the member."""
    songs = [f"s{i}" for i in range(20)]
    probs = rng.uniform(0.05, 1.0, (3, 20, 4)).astype(np.float32)
    probs /= probs.sum(axis=-1, keepdims=True)
    poisoned = probs.copy()
    poisoned[0] = np.nan  # the quarantined member's slot

    acq_a = Acquirer(songs, None, queries=5, mode="mc", seed=0)
    acq_b = Acquirer(songs, None, queries=5, mode="mc", seed=0)
    assert acq_a.select(poisoned) == acq_b.select(probs[1:])


def test_sanitizer_is_bit_identical_when_clean(rng):
    p = rng.uniform(0.01, 1.0, (4, 16, 4)).astype(np.float32)
    p /= p.sum(axis=-1, keepdims=True)
    out = np.asarray(_sanitize_member_rows(p))
    assert np.array_equal(out, p)  # unfaulted rankings cannot move


# -- transient retry -----------------------------------------------------


def test_retry_transient_bounds_and_jitter():
    calls, sleeps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientFault("blip")
        return 42

    assert retry_transient(flaky, attempts=3, seed=7,
                           sleep=sleeps.append) == 42
    assert len(calls) == 3 and len(sleeps) == 2
    assert all(d > 0 for d in sleeps)
    # seeded jitter: same seed → same backoff schedule
    calls2, sleeps2 = [], []

    def flaky2():
        calls2.append(1)
        if len(calls2) < 3:
            raise TransientFault("blip")
        return 0

    retry_transient(flaky2, attempts=3, seed=7, sleep=sleeps2.append)
    assert sleeps == sleeps2

    def always():
        raise TransientFault("down")

    with pytest.raises(TransientFault):
        retry_transient(always, attempts=2, sleep=lambda _: None)

    def hard():
        raise ValueError("not transient")

    calls3 = []
    with pytest.raises(ValueError):
        retry_transient(lambda: (calls3.append(1), hard()),
                        attempts=5, sleep=lambda _: None)
    assert len(calls3) == 1  # no retry on non-transient errors


def test_transient_scoring_fault_is_absorbed(tmp_path, rng):
    """A transient error in the (pure) scoring pass retries and the run's
    trajectory is identical to the unfaulted one."""
    data = _make_user(rng)
    base = tmp_path / "base"
    base.mkdir()
    res_base = _run(data, base)
    d = tmp_path / "u"
    d.mkdir()
    with faults.inject(FaultRule("pool.score", "transient", at=2)) as inj:
        res = _run(data, d)
    assert inj.fired
    assert res["trajectory"] == res_base["trajectory"]


# -- preemption ----------------------------------------------------------


class _CountingGuard:
    """Requests preemption after the Nth boundary check (stands in for a
    SIGTERM landing mid-run)."""

    def __init__(self, after: int):
        self.checks = 0
        self.after = after

    @property
    def requested(self) -> bool:
        self.checks += 1
        return self.checks > self.after


def test_preemption_finishes_commit_and_resumes(tmp_path, rng):
    data = _make_user(rng)
    base = tmp_path / "base"
    base.mkdir()
    res_base = _run(data, base)

    d = tmp_path / "u"
    d.mkdir()
    with pytest.raises(Preempted):
        _run(data, d, preemption=_CountingGuard(2))
    st = al_state.ALState.load(str(d))
    assert st is not None and st.next_epoch == 2  # committed, not torn
    assert not any(f.startswith(al_state.STAGING_PREFIX)
                   for f in os.listdir(str(d)))

    committee2 = workspace.load_committee(str(d))
    res2 = _run(data, d, committee=committee2)
    assert res2["trajectory"] == res_base["trajectory"]


def test_preemption_guard_catches_sigterm():
    assert EXIT_PREEMPTED == 75
    old = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        assert not g.requested
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(200):  # delivery is async; bounded wait
            if g.requested:
                break
            time.sleep(0.005)
        assert g.requested
    assert signal.getsignal(signal.SIGTERM) == old  # handler restored


# -- fault injector mechanics -------------------------------------------


def test_fault_rule_spec_parsing():
    rules = faults.parse_spec("checkpoint.write:kill@3,"
                              "member.predict:corrupt@1x-1,"
                              "pool.score:delay")
    assert [(r.point, r.action, r.at, r.times) for r in rules] == [
        ("checkpoint.write", "kill", 3, 1),
        ("member.predict", "corrupt", 1, -1),
        ("pool.score", "delay", 1, 1),
    ]
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.parse_spec("nope:kill")  # cetpu: noqa[fault-point-literal] deliberately-invalid point: pins the runtime rejection
    with pytest.raises(ValueError, match="bad CETPU_FAULTS entry"):
        faults.parse_spec("checkpoint.write")


def test_injector_counts_hits_deterministically():
    with faults.inject(FaultRule("pool.score", "raise", at=2)) as inj:
        faults.fire("pool.score")  # hit 1: below `at`
        with pytest.raises(InjectedFault):
            faults.fire("pool.score")  # hit 2: fires
        faults.fire("pool.score")  # hit 3: window passed
    assert inj.hits["pool.score"] == 3
    assert [f["hit"] for f in inj.fired] == [2]
    assert faults.active() is None  # uninstalled on exit


def test_member_filtered_rules_count_per_member_hits():
    """A member-filtered rule's ``at`` window indexes that member's OWN
    hits, not the global point counter — so "member m1's 2nd retrain"
    stays targeted no matter how many other members (or other users'
    committees in a fleet cohort) hit the point in between."""
    with faults.inject(FaultRule("member.retrain", "raise", at=2,
                                 member="m1")) as inj:
        faults.fire("member.retrain", member="m0")  # global hit 1
        faults.fire("member.retrain", member="m1")  # m1 hit 1: below at
        faults.fire("member.retrain", member="m0")
        with pytest.raises(InjectedFault):
            faults.fire("member.retrain", member="m1")  # m1 hit 2: fires
        faults.fire("member.retrain", member="m1")  # window passed
    assert inj.member_hits[("member.retrain", "m1")] == 3
    assert inj.hits["member.retrain"] == 5
    # a member-filtered rule never fires on a context-free hit
    with faults.inject(FaultRule("member.retrain", "raise", at=1, times=-1,
                                 member="m1")) as inj2:
        faults.fire("member.retrain")  # no member ctx: not m1's hit
    assert not inj2.fired


# -- satellites: state + recovery edge cases ----------------------------


def test_corrupt_state_file_loads_as_none_and_user_redoes(tmp_path):
    d = tmp_path / "u"
    d.mkdir()
    (d / al_state.STATE_FILE).write_text('{"next_epoch": 3, "trunc')
    with pytest.warns(UserWarning, match="unreadable AL state"):
        assert al_state.ALState.load(str(d)) is None

    # the existing redo path treats it as a pre-state crash: wiped clean
    pre = tmp_path / "pretrained"
    pre.mkdir()
    (pre / "classifier_gnb.it_0.pkl").write_bytes(b"x")
    users = str(tmp_path / "users")
    path, _ = workspace.create_user(users, str(pre), "u1", "mc")
    (tmp_path / "users" / "u1" / "mc" / al_state.STATE_FILE).write_text("{")
    (tmp_path / "users" / "u1" / "mc" / "junk").write_text("partial")
    with pytest.warns(UserWarning, match="unreadable AL state"):
        path2, skip2 = workspace.create_user(users, str(pre), "u1", "mc")
    assert not skip2 and not os.path.exists(os.path.join(path2, "junk"))


def test_schema_drift_state_fails_loud(tmp_path):
    """Valid JSON that doesn't fit the dataclass is a version mismatch,
    not bit-rot: it must fail loud instead of silently wiping the user."""
    d = tmp_path / "u"
    d.mkdir()
    (d / al_state.STATE_FILE).write_text(
        '{"next_epoch": 3, "no_such_field": 1}')
    with pytest.raises(ValueError, match="cannot read"):
        al_state.ALState.load(str(d))


def _mk_state(d, gen):
    al_state.ALState(gen, [0.5], [], [], [["s"]], [0, 0], "uint32",
                     "mc", 11).save(str(d))


def test_recover_non_integer_suffix_alongside_valid(tmp_path):
    d = tmp_path / "u"
    d.mkdir()
    (d / "classifier_gnb.m.pkl").write_text("old")
    _mk_state(d, 2)
    junk = d / f"{al_state.STAGING_PREFIX}foo"
    junk.mkdir()
    (junk / "classifier_gnb.m.pkl").write_text("junk")
    good = al_state.staging_dir(str(d), 2)
    os.makedirs(good)
    with open(os.path.join(good, "classifier_gnb.m.pkl"), "w") as f:
        f.write("gen2")
    al_state.recover_workspace(str(d))
    assert not junk.exists() and not os.path.exists(good)
    assert open(d / "classifier_gnb.m.pkl").read() == "gen2"


def test_recover_repeated_recovery_idempotent(tmp_path):
    d = tmp_path / "u"
    d.mkdir()
    (d / "classifier_gnb.m.pkl").write_text("old")
    _mk_state(d, 2)
    good = al_state.staging_dir(str(d), 2)
    os.makedirs(good)
    with open(os.path.join(good, "classifier_gnb.m.pkl"), "w") as f:
        f.write("gen2")
    for _ in range(3):
        al_state.recover_workspace(str(d))
        assert open(d / "classifier_gnb.m.pkl").read() == "gen2"
        # the last-good snapshot survives repeated recovery untouched
        prev = os.path.join(str(d), al_state.PREV_DIR)
        assert open(os.path.join(prev, "classifier_gnb.m.pkl")).read() \
            == "old"
        assert open(os.path.join(prev, al_state.PREV_MARKER)).read() == "2"


def test_recover_generation_mismatch_discards_stage(tmp_path):
    d = tmp_path / "u"
    d.mkdir()
    (d / "classifier_gnb.m.pkl").write_text("live")
    _mk_state(d, 2)
    stale = al_state.staging_dir(str(d), 5)  # neither st.next_epoch nor junk
    os.makedirs(stale)
    with open(os.path.join(stale, "classifier_gnb.m.pkl"), "w") as f:
        f.write("wrong-gen")
    al_state.recover_workspace(str(d))
    assert not os.path.exists(stale)
    assert open(d / "classifier_gnb.m.pkl").read() == "live"


def test_reentered_promotion_keeps_partial_snapshot(tmp_path):
    """Crash mid-promote, then recovery re-enters the promote: the
    already-accumulated previous-generation copies must be KEPT (wiping
    them and re-marking COMPLETE would let a later rollback restore a
    mixed-generation committee).  Constructed state: file A was already
    promoted (its gen-1 copy lives only in the snapshot), file B was not."""
    d = tmp_path / "u"
    d.mkdir()
    _mk_state(d, 1)
    os.replace(os.path.join(str(d), al_state.STATE_FILE),
               os.path.join(str(d), al_state.STATE_FILE
                            + al_state.PREV_STATE_SUFFIX))
    _mk_state(d, 2)
    (d / "classifier_gnb.a.pkl").write_text("A2")  # promoted before crash
    (d / "classifier_gnb.b.pkl").write_text("B1")  # not yet promoted
    prev = d / al_state.PREV_DIR
    prev.mkdir()
    (prev / al_state.PREV_GEN_MARKER).write_text("2")
    (prev / "classifier_gnb.a.pkl").write_text("A1")
    stage = al_state.staging_dir(str(d), 2)
    os.makedirs(stage)
    with open(os.path.join(stage, "classifier_gnb.b.pkl"), "w") as f:
        f.write("B2")

    al_state.recover_workspace(str(d))  # re-entered promote completes
    assert open(d / "classifier_gnb.a.pkl").read() == "A2"
    assert open(d / "classifier_gnb.b.pkl").read() == "B2"
    assert open(prev / al_state.PREV_MARKER).read() == "2"
    assert open(prev / "classifier_gnb.a.pkl").read() == "A1"  # kept!

    assert al_state.rollback_workspace(str(d))  # snapshot is truly complete
    assert open(d / "classifier_gnb.a.pkl").read() == "A1"
    assert open(d / "classifier_gnb.b.pkl").read() == "B1"
    assert al_state.ALState.load(str(d)).next_epoch == 1


def test_stale_snapshot_of_other_generation_is_replaced(tmp_path):
    d = tmp_path / "u"
    d.mkdir()
    (d / "classifier_gnb.m.pkl").write_text("g1")
    _mk_state(d, 2)
    prev = d / al_state.PREV_DIR
    prev.mkdir()
    (prev / al_state.PREV_GEN_MARKER).write_text("1")  # older generation
    (prev / "classifier_gnb.m.pkl").write_text("g0-stale")
    stage = al_state.staging_dir(str(d), 2)
    os.makedirs(stage)
    with open(os.path.join(stage, "classifier_gnb.m.pkl"), "w") as f:
        f.write("g2")
    al_state.recover_workspace(str(d))
    assert open(d / "classifier_gnb.m.pkl").read() == "g2"
    # the stale gen-0 copy was dropped; the snapshot now holds gen 1
    assert open(prev / "classifier_gnb.m.pkl").read() == "g1"
    assert open(prev / al_state.PREV_GEN_MARKER).read() == "2"


def test_rollback_refuses_incomplete_or_mismatched_snapshot(tmp_path):
    d = tmp_path / "u"
    d.mkdir()
    _mk_state(d, 2)
    assert not al_state.rollback_workspace(str(d))  # nothing retained
    prev = d / al_state.PREV_DIR
    prev.mkdir()
    (prev / "classifier_gnb.m.pkl").write_text("g1")
    assert not al_state.rollback_workspace(str(d))  # no COMPLETE marker
    (prev / al_state.PREV_MARKER).write_text("7")   # wrong generation
    (d / (al_state.STATE_FILE + al_state.PREV_STATE_SUFFIX)).write_text(
        (d / al_state.STATE_FILE).read_text())
    assert not al_state.rollback_workspace(str(d))
    assert (prev / "classifier_gnb.m.pkl").exists()  # untouched


# -- AsyncCheckpointer context manager (satellite) -----------------------


def test_async_checkpointer_context_manager_releases_worker():
    done = []
    with AsyncCheckpointer() as ck:
        ck.submit(lambda: done.append(1))
    assert done == [1]
    with pytest.raises(RuntimeError):  # worker released: pool is shut down
        ck.submit(lambda: None)


def test_async_checkpointer_surfaces_deferred_error_on_clean_exit():
    with pytest.raises(RuntimeError, match="disk full"):
        with AsyncCheckpointer() as ck:
            ck.submit(lambda: (_ for _ in ()).throw(RuntimeError("disk full")))


def test_async_checkpointer_does_not_mask_loop_error():
    with pytest.raises(KeyError, match="root cause"):
        with AsyncCheckpointer() as ck:
            ck.submit(lambda: (_ for _ in ()).throw(RuntimeError("deferred")))
            raise KeyError("root cause")
