"""Gray-failure resilience (PR 20): the stall/slow fault grammar, the
peer-relative slowness detector, and the journaled suspicion →
probation → drain escalation ladder.

Tier-1 keeps the pure kernels with threshold tables (``_gray_outliers``
/ ``gray_suspect_alerts`` evidence merge, the ``gray_rung`` /
``probation_clear`` / ``degrade_depth`` ladder gates), the grown
``CETPU_FAULTS`` grammar (``stall=`` / ``slow=`` with clean parse
errors) and its action semantics (a stall holds the hit, a slow factor
is armed by ``fire`` and honored by the site's ``slow_hold`` bracket),
the REPLAYED ``probation`` journal category (fold, compaction
round-trip, append/validate rows), the config validation table, the
committee depth dial (CNN seats kept first, ``min_members`` floor), the
``cetpu-top`` staleness cue and the ``deadline-discipline`` lint rule —
plus the DETERMINISTIC fake-worker drills: a slow-not-dead host climbs
the full ladder (suspect alert with evidence → journaled probation →
gray_drain moves every unresolved user over the ack-gated protocol), a
recovered host earns its lift, and a coordinator SIGKILL at each new
fault point (``fabric.gray``, the gray ``fabric.remedy`` decision,
``serve.feed.poll``) replays from the journal to the SAME rung with
exactly one owner per user.  The real-subprocess acceptance drill is
``scripts/gray_check.sh`` (fault-matrix tier)."""

import json
import os
import time

import pytest

from consensus_entropy_tpu.obs.alerts import (
    AlertWatcher,
    _gray_outliers,
    gray_suspect_alerts,
)
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
    FleetServer,
    degrade_depth,
    gray_rung,
    probation_clear,
    validate_journal_file,
)
from consensus_entropy_tpu.serve.journal import JournalState, JsonlTail
from tests.test_elastic import _FakeWorker
from tests.test_remedy import _Rec, _journal_records, _work

pytestmark = [pytest.mark.serve, pytest.mark.faults]


# -- the grown CETPU_FAULTS grammar: stall= / slow= ------------------------


def test_parse_spec_gray_actions():
    r, = faults.parse_spec("serve.dispatch:stall=2.5@1x-1")
    assert (r.point, r.action, r.stall_s) == \
        ("serve.dispatch", "stall", 2.5)
    assert (r.at, r.times) == (1, -1)
    r, = faults.parse_spec("serve.feed.poll:slow=3")
    assert r.action == "slow" and r.slow_factor == 3.0
    r, = faults.parse_spec("io.fsync:stall=inf")
    assert r.stall_s == float("inf")
    # bare stall/slow keep the rule-field defaults
    r, = faults.parse_spec("io.fsync:stall")
    assert r.stall_s == 1.0
    r, = faults.parse_spec("io.fsync:slow")
    assert r.slow_factor == 2.0


def test_parse_spec_gray_errors():
    with pytest.raises(ValueError, match="takes no '=value'"):
        faults.parse_spec("io.fsync:kill=3")
    with pytest.raises(ValueError, match="malformed float"):
        faults.parse_spec("io.fsync:stall=abc")
    with pytest.raises(ValueError, match="slow_factor"):
        faults.parse_spec("io.fsync:slow=0.5")
    with pytest.raises(ValueError, match="stall_s"):
        faults.parse_spec("io.fsync:stall=-1")


def test_stall_action_holds_the_hit():
    with faults.inject(FaultRule("serve.feed.poll", "stall",
                                 stall_s=0.05)) as inj:
        t0 = time.perf_counter()
        faults.fire("serve.feed.poll")
        assert time.perf_counter() - t0 >= 0.05
        assert inj.fired and inj.fired[0]["action"] == "stall"


def test_slow_action_arms_fire_and_honors_slow_hold():
    with faults.inject(FaultRule("serve.feed.poll", "slow",
                                 slow_factor=3.0, times=-1)):
        faults.fire("serve.feed.poll")  # arms this thread's factor
        t0 = time.perf_counter()
        faults.slow_hold("serve.feed.poll", 0.05)
        assert time.perf_counter() - t0 >= 0.05 * (3.0 - 1.0) - 0.01
        # the pending factor is CONSUMED: a hold without a new fire is
        # free (the stickiness lives in the rule's hit window, re-armed
        # per fire)
        t0 = time.perf_counter()
        faults.slow_hold("serve.feed.poll", 0.05)
        assert time.perf_counter() - t0 < 0.04
    # no injector installed: the module-level hook is a cheap no-op
    faults.slow_hold("serve.feed.poll", 5.0)


def test_feed_poll_fault_point_fires_in_jsonl_tail(tmp_path):
    path = str(tmp_path / "feed.jsonl")
    with open(path, "w") as f:
        f.write('{"user": "u0"}\n')
    tail = JsonlTail(path)
    with faults.inject(FaultRule("serve.feed.poll", "kill", at=1)):
        with pytest.raises(InjectedKill):
            tail.poll()
    # the lagging-tail arm: a slow rule brackets the poll (the read
    # still completes and returns the records)
    with faults.inject(FaultRule("serve.feed.poll", "slow",
                                 slow_factor=2.0)) as inj:
        assert [r for r, _ in tail.poll()] == [{"user": "u0"}]
        assert inj.fired and inj.fired[0]["action"] == "slow"


# -- the peer-relative detection kernels -----------------------------------


def test_gray_outlier_kernel_threshold_table():
    table = [
        # one sick host against healthy peers
        ({"h0": 9.0, "h1": 1.0, "h2": 1.2}, ["h0"]),
        # exactly ratio * peer fires (>= gate; binary-exact values)
        ({"h0": 3.75, "h1": 1.25, "h2": 1.25}, ["h0"]),
        # just under the ratio gate
        ({"h0": 3.74, "h1": 1.25, "h2": 1.25}, []),
        # under the absolute floor: idle-fleet noise never flags
        ({"h0": 0.9, "h1": 0.1, "h2": 0.1}, []),
        # uniformly slow fleet is LOAD, not gray
        ({"h0": 9.0, "h1": 9.0, "h2": 9.0}, []),
        # fewer than two observed hosts: no peers, no outliers
        ({"h0": 9.0}, []),
        ({"h0": 9.0, "h1": None}, []),
        # None = no observation, excluded from both sides
        ({"h0": 9.0, "h1": None, "h2": 0.5}, ["h0"]),
        # zero-valued peers: the absolute floor is the only gate left
        ({"h0": 2.0, "h1": 0.0, "h2": 0.0}, ["h0"]),
    ]
    for values, want in table:
        got = [h for h, _v, _p in _gray_outliers(values, ratio=3.0,
                                                 min_abs_s=1.0)]
        assert got == want, (values, got, want)


def test_gray_suspect_alerts_merge_signals_with_evidence():
    alerts = gray_suspect_alerts(
        append_ages={"h0": 9.0, "h1": 0.5, "h2": 0.4},
        ack_lags={"h0": 0.0, "h1": 0.0, "h2": 0.0},
        lease_ages={"h0": 0.2, "h1": 0.2, "h2": 0.2},
        step_walls={"h0": 6.0, "h1": 1.0, "h2": 1.0})
    assert [a["host"] for a in alerts] == ["h0"]
    a = alerts[0]
    assert a["kind"] == "gray_suspect" and a["key"] == "h0"
    # every firing signal listed, each with its value/peer evidence
    assert a["signals"] == ["append_age", "step_wall"]
    assert a["append_age_s"] == 9.0 and a["append_age_peer_s"] == 0.45
    assert a["step_wall_s"] == 6.0 and a["step_wall_peer_s"] == 1.0
    # no signals, no alerts; a healthy fleet is silent
    assert gray_suspect_alerts() == []
    assert gray_suspect_alerts(
        step_walls={"h0": 1.0, "h1": 1.0, "h2": 1.1}) == []


def test_gray_rung_ladder_table():
    for held_since, want in [(None, "healthy"), (10.0, "suspect"),
                             (8.5, "suspect"), (8.0, "probation"),
                             (4.5, "probation"), (4.0, "drain"),
                             (0.0, "drain")]:
        got = gray_rung(held_since, 10.0, hold_s=2.0, drain_s=4.0)
        assert got == want, (held_since, got, want)


def test_probation_clear_and_degrade_depth_tables():
    assert not probation_clear(None, 10.0, clear_s=4.0)   # still suspect
    assert not probation_clear(7.0, 10.0, clear_s=4.0)    # not clean long enough
    assert probation_clear(6.0, 10.0, clear_s=4.0)        # >= gate lifts
    assert not degrade_depth(False, 99.0, hold_s=2.0)     # healthy host: load problem
    assert not degrade_depth(True, None, hold_s=2.0)      # not burning
    assert not degrade_depth(True, 1.9, hold_s=2.0)       # burn not sustained
    assert degrade_depth(True, 2.0, hold_s=2.0)


# -- the journaled (replayed) probation category ---------------------------


def test_journal_probation_folds_and_replays(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp)
    j.append("probation", host="h1", on=True)
    j.append("probation", host="h2", on=True)
    j.append("probation", host="h1", on=False)
    assert j.state.probation == {"h2"}
    j.close()
    st = AdmissionJournal(jp).state
    assert st.probation == {"h2"}
    assert validate_journal_file(jp) == []
    # the compaction checkpoint round-trips the set
    assert JournalState.from_dict(st.to_dict()).probation == {"h2"}


def test_journal_probation_survives_compaction_cycles(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp, compact_bytes=500)
    for i in range(40):
        j.append("probation", host=f"h{i % 3}", on=(i % 2 == 0))
    assert j.compactions >= 1
    want = j.state.probation
    j.close()
    assert AdmissionJournal(jp).state.probation == want == {"h2"}
    assert validate_journal_file(jp) == []


def test_journal_probation_append_and_validate_rows(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    j = AdmissionJournal(jp)
    with pytest.raises(ValueError, match="needs host= and on="):
        j.append("probation", host="h1")
    with pytest.raises(ValueError, match="needs host= and on="):
        j.append("probation", on=True)
    j.append("probation", host="h1", on=True)
    # a hand-forged record missing on= is a validation finding
    j._file.append({"event": "probation", "seq": j.state.seq + 1,
                    "host": "h2"})
    j.close()
    errs = validate_journal_file(jp)
    assert errs and any("probation" in e for e in errs)


def test_fabric_config_gray_validation_table():
    ok = FabricConfig(hosts=2, min_hosts=2, max_hosts=2, gray=True)
    assert ok.gray and ok.elastic
    with pytest.raises(ValueError, match="gray requires the elastic"):
        FabricConfig(hosts=2, gray=True)
    with pytest.raises(ValueError, match="gray_ratio"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, gray=True,
                     gray_ratio=0.5)
    with pytest.raises(ValueError, match="gray_min_s"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, gray=True,
                     gray_min_s=-1.0)
    with pytest.raises(ValueError, match="gray_hold_s/gray_drain_s"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, gray=True,
                     gray_hold_s=-1.0)
    with pytest.raises(ValueError,
                       match="depth_on_burn requires the gray"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2,
                     depth_on_burn=True)
    with pytest.raises(ValueError, match="depth_hold_s"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, gray=True,
                     depth_on_burn=True, depth_hold_s=-1.0)


# -- the degradation dial: committee depth cap -----------------------------


class _StubMember:
    def __init__(self, name):
        self.name = name


def test_committee_depth_cap_keeps_cnn_seats_first():
    from consensus_entropy_tpu.models.committee import Committee

    c = Committee([_StubMember("a"), _StubMember("b")], [],
                  min_members=1)
    cnns = [_StubMember("c1"), _StubMember("c2")]
    # duck-typed: _active_pair reads the member lists only, and real
    # CNN members carry frontend-geometry configs the stub needn't
    c.cnn_members = cnns
    assert c.active_size == 4
    c.depth_cap = 3
    assert c.active_cnn_members == cnns  # the fast stage keeps its seats
    assert [m.name for m in c.active_host_members] == ["a"]
    # the dial is floored at min_members (never exhausts the committee)
    c.depth_cap = 0
    assert c.active_size == 1 and c.active_cnn_members == cnns[:1]
    c.depth_cap = None  # restore is behavior-identical to the default
    assert c.active_size == 4


def test_scheduler_depth_dial_validates_and_applies():
    from consensus_entropy_tpu.config import ALConfig
    from consensus_entropy_tpu.fleet.scheduler import FleetScheduler

    sched = FleetScheduler(ALConfig(queries=1, epochs=1, mode="mc",
                                    seed=0))
    assert sched.depth == "full"
    with pytest.raises(ValueError, match="unknown depth"):
        sched.set_depth("turbo")

    class _C:
        depth_cap = None
        min_members = 2

    c = _C()
    sched.set_depth("cheap")
    sched._apply_depth(c)
    assert c.depth_cap == 2
    sched.set_depth("full")
    sched._apply_depth(c)
    assert c.depth_cap is None


def test_fleet_server_depth_delegates_to_scheduler():
    class _Sched:
        def __init__(self):
            self.calls = []

        def set_depth(self, depth):
            self.calls.append(depth)

    srv = FleetServer.__new__(FleetServer)
    srv.scheduler = _Sched()
    srv.set_depth("cheap")
    assert srv.scheduler.calls == ["cheap"]


# -- deterministic fake-fleet gray drills ----------------------------------


class _GrayWorker(_FakeWorker):
    """``_FakeWorker`` plus the step-wall advertisement: the real
    worker's lease heartbeat carries the scheduler's dispatch-wall EMA
    (``step_ema_s``) — the drill dials one host's EMA up to model a
    slow-not-dead device, everything else stays journal/file-driven."""

    def __init__(self, fabric_dir, host_id, step_ema_s=0.5):
        self.step_ema_s = step_ema_s
        super().__init__(fabric_dir, host_id)

    def beat(self):
        if self.dead:
            return
        tmp = self.paths["lease"] + ".tmp"
        with open(tmp, "wb") as f:
            f.write(json.dumps(
                {"host": self.host_id, "pid": os.getpid(),
                 "t": time.time(),
                 "step_ema_s": self.step_ema_s}).encode())
        os.replace(tmp, self.paths["lease"])


def _gray_fleet(tmp_path, config, users, pools, script, *, workers=None,
                alerts=None, slow=("h0",)):
    """A 3-host fake fleet where hosts named in ``slow`` advertise a
    gray step wall (9 s vs the 0.5 s fleet baseline).  ``workers`` may
    be passed to keep a killed incarnation's hosts for exactly-once
    accounting across reruns (the ``_remedy_fleet`` discipline)."""
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir, exist_ok=True)
    journal = AdmissionJournal(
        os.path.join(fabric_dir, "serve_journal.jsonl"))
    workers = {} if workers is None else workers

    def spawn(host_id):
        workers[host_id] = _GrayWorker(
            fabric_dir, host_id,
            step_ema_s=9.0 if host_id in slow else 0.5)
        return workers[host_id]

    state = {"round": 0}

    def on_poll(coord):
        state["round"] += 1
        if state["round"] > 2000:
            raise AssertionError("gray drill wedged: "
                                 f"unresolved={sorted(coord._unresolved)}")
        for w in list(workers.values()):
            w.pump()
        script(state["round"], coord, workers)

    coord = FabricCoordinator(journal, fabric_dir, config,
                              on_poll=on_poll, alerts=alerts)
    try:
        summary = coord.run(users, spawn, pools=pools)
    finally:
        journal.close()
    return summary, coord, workers, fabric_dir


def _gray_cfg(**kw):
    base = dict(hosts=3, min_hosts=3, max_hosts=3, poll_s=0.01,
                drain_timeout_s=0.2, placement="load",
                gray=True, gray_ratio=3.0, gray_min_s=1.0,
                gray_hold_s=0.0, gray_drain_s=0.03, gray_clear_s=600.0)
    base.update(kw)
    return FabricConfig(**base)


def test_gray_drill_climbs_to_probation_and_drain(tmp_path):
    """The full ladder: h0's advertised step wall skews 18x over its
    peers — the gray_suspect alert fires with step-wall evidence, the
    coordinator journals PROBATION (one record, one counter tick), the
    sustained suspicion escalates to gray_drain, and every one of h0's
    users migrates over the ack-gated drop path to finish elsewhere.
    h0 is never retired: probation + an empty assignment hold the line."""
    users = [f"u{i}" for i in range(9)]
    pools = {u: 30 for u in users}
    rep = _Rec()

    def script(rnd, coord, workers):
        for hid, w in workers.items():
            if hid == "h0":
                continue  # gray: acks the control plane, admits nothing
            _work(w)

    summary, coord, workers, fabric_dir = _gray_fleet(
        tmp_path, _gray_cfg(), users, pools, script,
        alerts=AlertWatcher(rep))
    assert sorted(summary["finished"]) == users
    assert summary["probations"] == 1 and summary["gray_drains"] == 1
    assert summary["depth_changes"] == 0  # dial default-off
    # exactly one owner per user; the gray host ran none of them
    ran = [u for w in workers.values() for u in w.finished]
    assert sorted(ran) == users and not workers["h0"].finished
    recs = _journal_records(fabric_dir)
    probs = [(r["host"], r["on"]) for r in recs
             if r["event"] == "probation"]
    assert probs == [("h0", True)]
    remedies = [(r["host"], r["action"]) for r in recs
                if r["event"] == "remedy"]
    assert remedies == [("h0", "gray_drain")]
    # the alert carried its evidence: the step-wall value/peer pair
    gray = [kw for k, kw in rep.events
            if k == "alert" and kw["kind"] == "gray_suspect"]
    assert gray and all(a["host"] == "h0" for a in gray)
    assert "step_wall" in gray[0]["signals"]
    assert gray[0]["step_wall_s"] >= 3.0 * gray[0]["step_wall_peer_s"]
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    assert validate_journal_file(jp) == []
    # the rung REPLAYS: probation is journal state, not coordinator RAM
    st = AdmissionJournal(jp).state
    assert st.probation == {"h0"}
    assert AdmissionJournal(jp).state.probation == st.probation


def test_gray_probation_lifts_after_recovery(tmp_path):
    """The down-ladder: once h0's step wall returns to the fleet
    baseline and stays clean past ``gray_clear_s``, probation lifts
    (journaled ``on=False``), the host re-enters rotation and finishes
    the users it kept — the ladder never drained them."""
    users = [f"u{i}" for i in range(6)]
    pools = {u: 30 for u in users}
    cfg = _gray_cfg(gray_drain_s=600.0, gray_clear_s=0.02)
    state = {"probed": False, "lifted": False}

    def script(rnd, coord, workers):
        st = coord.journal.state
        if "h0" in st.probation and not state["probed"]:
            state["probed"] = True
            workers["h0"].step_ema_s = 0.5  # the slowness clears
        if state["probed"] and not st.probation:
            state["lifted"] = True
        if state["lifted"]:
            for w in workers.values():
                _work(w)

    summary, coord, workers, fabric_dir = _gray_fleet(
        tmp_path, cfg, users, pools, script)
    assert sorted(summary["finished"]) == users
    assert summary["probations"] == 1 and summary["gray_drains"] == 0
    recs = _journal_records(fabric_dir)
    probs = [(r["host"], r["on"]) for r in recs
             if r["event"] == "probation"]
    assert probs == [("h0", True), ("h0", False)]
    assert workers["h0"].finished  # back in rotation with its users
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    assert AdmissionJournal(jp).state.probation == set()
    assert validate_journal_file(jp) == []


@pytest.mark.parametrize("point,at,probation_before", [
    # killed at the rung transition, BEFORE the probation append: the
    # decision never journaled, the rerun re-derives it from evidence
    ("fabric.gray", 1, []),
    # killed at the gray_drain decision: probation is already durable,
    # the drain record is not — the rerun resumes ON the same rung
    ("fabric.remedy", 1, [("h0", True)]),
    # killed mid-feed-read (the lagging-tail seam): no decision state
    # is tied to a poll, so the rerun just replays the journal
    ("serve.feed.poll", 5, None),
])
def test_gray_kill_matrix_replays_to_same_rung(tmp_path, point, at,
                                               probation_before):
    users = [f"u{i}" for i in range(9)]
    pools = {u: 30 for u in users}
    cfg = _gray_cfg()

    def script1(rnd, coord, workers):
        for hid, w in workers.items():
            if hid != "h0":
                _work(w)

    jp = str(tmp_path / "fabric" / "serve_journal.jsonl")
    w1 = {}
    with faults.inject(FaultRule(point, "kill", at=at)):
        with pytest.raises(InjectedKill):
            _gray_fleet(tmp_path, cfg, users, pools, script1,
                        workers=w1)
    recs_mid = _journal_records(str(tmp_path / "fabric"))
    probs_mid = [(r["host"], r["on"]) for r in recs_mid
                 if r["event"] == "probation"]
    if probation_before is not None:
        # fired-before-append: the killed decision left no half-record
        assert probs_mid == probation_before
        assert [r for r in recs_mid if r["event"] == "remedy"] == []
    replayed = {h for h, on in probs_mid if on}
    done1 = set(AdmissionJournal(jp).state.finished)
    state = {"checked": False}

    def script2(rnd, coord, workers):
        if not state["checked"]:
            state["checked"] = True
            # replay-to-same-rung: the fresh coordinator starts from
            # the journaled probation set, not from scratch
            assert coord.journal.state.probation == replayed
        for w in workers.values():
            if w.dead:
                continue
            # stale feed lines re-deliver users the first incarnation
            # already finished; they resolve from their complete
            # workspaces, modeled by dropping them without running
            for uid in list(w.queued):
                if uid in done1:
                    w.queued.remove(uid)
            _work(w)

    w2 = {}
    summary, coord, workers, fabric_dir = _gray_fleet(
        tmp_path, cfg, users, pools, script2, workers=w2, slow=())
    assert sorted(list(done1) + summary["finished"]) == users
    # exactly one owner per user ACROSS BOTH incarnations
    ran = [u for w in list(w1.values()) + list(w2.values())
           for u in w.finished]
    assert sorted(ran) == users
    assert validate_journal_file(jp) == []
    # h0 healthy in the rerun: no NEW probation was ever derived, and
    # a rung journaled before the kill is still the replayed state
    # (clear_s is huge, so nothing lifted mid-run)
    assert AdmissionJournal(jp).state.probation == replayed


# -- the cetpu-top staleness cue -------------------------------------------


def test_top_flags_and_dims_stale_snapshots():
    from consensus_entropy_tpu.cli.top import (
        STALE_INTERVALS,
        _stale_bound,
        render,
    )

    now = 1000.0
    fresh = {"host": "w0", "t": now - 1.0, "interval_s": 1.0,
             "live": 1, "target_live": 2}
    stale = {"host": "w1", "t": now - 4.0, "interval_s": 1.0,
             "live": 1, "target_live": 2}
    out = render({"w0": fresh, "w1": stale}, now=now)
    lines = out.splitlines()
    w0 = next(ln for ln in lines if "[w0]" in ln)
    w1 = next(ln for ln in lines if "[w1]" in ln)
    assert "STALE" not in w0 and "\x1b[2m" not in w0
    assert "STALE" in w1 and w1.startswith("\x1b[2m")  # dimmed, with age
    assert "4.0s" in w1
    # the bound is the WRITER'S OWN cadence when advertised; --stale-s
    # is the fallback for pre-interval snapshots
    assert _stale_bound({"interval_s": 2.0}, 10.0) == \
        2.0 * STALE_INTERVALS
    assert _stale_bound({}, 10.0) == 10.0
    assert _stale_bound({"interval_s": 0}, 10.0) == 10.0
    old_no_interval = {"host": "w2", "t": now - 4.0, "live": 1,
                       "target_live": 2}
    out = render({"w2": old_no_interval}, now=now, stale_s=10.0)
    assert "STALE" not in out  # fallback bound, not yet stale


def test_status_writer_stamps_its_cadence(tmp_path):
    from consensus_entropy_tpu.obs.status import StatusWriter, read_status

    w = StatusWriter(str(tmp_path), "w0", interval_s=2.5,
                     clock=lambda: 7.0)
    w.write({"live": 1})
    snap = read_status(w.path)
    assert snap["interval_s"] == 2.5 and snap["t"] == 7.0


# -- the deadline-discipline lint rule -------------------------------------


def test_deadline_discipline_flags_unbounded_waits():
    from tests.test_lint import REPLAY_FILE, rules_fired

    sel = ["deadline-discipline"]
    assert rules_fired("""
        def close(worker):
            worker.thread.join()
    """, REPLAY_FILE, select=sel) == ["deadline-discipline"]
    assert rules_fired("""
        def close(worker):
            worker.thread.join(timeout=2.0)
    """, REPLAY_FILE, select=sel) == []
    assert rules_fired("""
        import time

        def watch(path):
            while True:
                time.sleep(0.1)
    """, REPLAY_FILE, select=sel) == ["deadline-discipline"]


def test_deadline_discipline_allows_bounded_loops():
    from tests.test_lint import PKG_FILE, REPLAY_FILE, rules_fired

    sel = ["deadline-discipline"]
    # a deadline read through the injected clock seam bounds the loop
    assert rules_fired("""
        import time

        class W:
            def watch(self, deadline):
                while True:
                    if self._clock() > deadline:
                        break
                    time.sleep(0.1)
    """, REPLAY_FILE, select=sel) == []
    # a real exit condition is bounded by construction
    assert rules_fired("""
        import time

        def drain(q):
            while q:
                q.pop()
                time.sleep(0.01)
    """, REPLAY_FILE, select=sel) == []
    # scoped to serve/: the same bare join elsewhere is not this
    # plane's contract
    assert rules_fired("""
        def close(worker):
            worker.thread.join()
    """, PKG_FILE, select=sel) == []
