"""SLO-aware admission: adaptive bucket planner, priority classes,
predictive batch-forming (``serve.planner``).

Tier-1 (un-marked) keeps the pure-host units — quantile-sketch exactness
vs numpy / merge associativity / serialization, edge derivation, the
pinned hold-decision tables, the class-aware queue with its starvation
guard, the ``ServeConfig`` bucket-widths validation bugfix, and the
journal-replay edge determinism — plus ONE small two-class serve smoke
(paid for by demoting the flaky-mix smoke to slow, see
``tests/test_serve_faults.py``).  The six-mode parity matrix and the
planner restart drill are ``slow`` (``scripts/fault_matrix.sh`` /
``scripts/slo_check.sh`` run them in CI's slow lane).

Parity is exact (``==`` on float lists) throughout: holds and edges only
change WHEN work batches and at what pad, never what it computes —
padding does not change selections, and the stacked scorers are
bit-identical to the single-user fns.
"""

import dataclasses
import json

import numpy as np
import pytest

from consensus_entropy_tpu.al import workspace
from consensus_entropy_tpu.al.loop import ALLoop
from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, FleetUser
from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.obs.metrics import QuantileSketch
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    AdmissionPlanner,
    AdmissionQueue,
    BucketRouter,
    FleetServer,
    ServeConfig,
    admission_hold,
    derive_edges,
    dispatch_hold,
    validate_bucket_widths,
)
from tests.test_fleet import _cfg, _committee, _user_data

pytestmark = pytest.mark.serve


# -- quantile sketch (pure host) ------------------------------------------


def test_sketch_exact_vs_numpy_below_reservoir():
    """While the reservoir holds, every percentile is BIT-identical to
    numpy's linear interpolation — the planner's edge derivation is
    numpy-exact until the bound, like the obs Histogram it extends."""
    rng = np.random.default_rng(7)
    xs = rng.integers(8, 4000, size=600)
    sk = QuantileSketch()
    for x in xs:
        sk.add(int(x))
    assert sk.exact
    for q in (1, 10, 25, 50, 66.6, 75, 90, 95, 99, 100):
        assert sk.percentile(q) == np.percentile(xs, q)


def test_sketch_past_reservoir_upper_bounds():
    """Past ``max_samples`` the reservoir is spent: percentiles fall back
    to log-bucket upper edges — an UPPER bound on the true quantile (the
    conservative direction: derived bucket edges get wider, never too
    tight to fit the pools that produced them)."""
    rng = np.random.default_rng(8)
    xs = rng.integers(8, 4000, size=500)
    sk = QuantileSketch(max_samples=64)
    for x in xs:
        sk.add(int(x))
    assert not sk.exact
    for q in (50, 90, 99):
        assert sk.percentile(q) >= np.percentile(xs, q)
    assert sk.percentile(100) == float(np.max(xs))


def test_sketch_merge_associative_and_exactness_rule():
    """Merge associativity (the fabric-hosts contract): bucket counts
    add, and the exact reservoir survives iff the COMBINED count fits the
    bound — a decision independent of merge order."""
    rng = np.random.default_rng(9)
    xs = rng.integers(8, 2000, size=90)
    parts = [xs[:30], xs[30:55], xs[55:]]

    def sketch(vals, max_samples=4096):
        sk = QuantileSketch(max_samples=max_samples)
        for v in vals:
            sk.add(int(v))
        return sk

    a, b, c = (sketch(p) for p in parts)
    left = QuantileSketch.from_dict(a.to_dict()).merge(b).merge(c)
    right = QuantileSketch.from_dict(a.to_dict()).merge(
        QuantileSketch.from_dict(b.to_dict()).merge(c))
    assert (left.n, left.total, left.min, left.max) \
        == (right.n, right.total, right.min, right.max)
    assert left._buckets == right._buckets
    assert sorted(left._samples) == sorted(right._samples)
    for q in (25, 50, 75, 95, 100):
        assert left.percentile(q) == right.percentile(q) \
            == np.percentile(xs, q)
    # overflow collapse is order-independent too: 30+25+35 > bound=48
    a, b, c = (sketch(p, max_samples=48) for p in parts)
    left = QuantileSketch.from_dict(a.to_dict()).merge(b).merge(c)
    right = QuantileSketch.from_dict(a.to_dict()).merge(
        QuantileSketch.from_dict(b.to_dict()).merge(c))
    assert left._samples is None and right._samples is None
    assert left._buckets == right._buckets
    for q in (50, 95):
        assert left.percentile(q) == right.percentile(q)
    # geometry mismatch fails loudly instead of merging garbage
    with pytest.raises(ValueError, match="geometry"):
        sketch(parts[0]).merge(sketch(parts[1], max_samples=48))


def test_sketch_dict_roundtrip():
    sk = QuantileSketch()
    for v in (10, 20, 300, 4000):
        sk.add(v)
    rt = QuantileSketch.from_dict(json.loads(json.dumps(sk.to_dict())))
    assert (rt.n, rt.total, rt.min, rt.max) \
        == (sk.n, sk.total, sk.min, sk.max)
    for q in (0, 50, 100):
        assert rt.percentile(q) == sk.percentile(q)


# -- edge derivation (pure host) ------------------------------------------


def test_derive_edges_deterministic_padded_and_total():
    sk = QuantileSketch()
    for v in [120] * 32 + [480] * 8:
        sk.add(v)
    edges = derive_edges(sk, n_buckets=4)
    # quantiles of a two-point distribution collapse onto the observed
    # sizes: the operator-guess-free geometry is TIGHT (120, not 128)
    assert edges == (120, 480)
    assert edges == derive_edges(sk, n_buckets=4)  # deterministic
    # every edge is a PAD_MULTIPLE multiple; the empty sketch derives
    # nothing (the router keeps its pow2 fallback)
    assert all(e % 8 == 0 for e in derive_edges(sk, n_buckets=7))
    assert derive_edges(QuantileSketch()) == ()
    # routing stays total: a pool above every edge falls through to pow2
    r = BucketRouter()
    r.update(edges)
    assert r.width_for(100) == 120
    assert r.width_for(481) == 512


# -- hold decisions (pure host, pinned) -----------------------------------


def test_admission_hold_decision_table():
    """The intake-side batch-forming kernel, pinned on synthetic
    telemetry: hold only while the predicted marginal wait raises the
    gang without breaching SLO headroom."""
    kw = dict(gap_s=0.2, headroom_s=10.0, max_hold_s=2.0)
    # gang already fills the free slots -> no hold
    assert admission_hold(free=2, queued=2, **kw) == 0.0
    assert admission_hold(free=0, queued=0, **kw) == 0.0
    # predicted fill time for the remaining slots, capped
    assert admission_hold(free=4, queued=2, **kw) \
        == pytest.approx(0.4)
    assert admission_hold(free=4, queued=0, gap_s=1.0, headroom_s=10.0,
                          max_hold_s=2.0) == 2.0  # operator cap
    # SLO guard: predicted wait past headroom, or headroom spent -> 0
    assert admission_hold(free=4, queued=0, gap_s=5.0, headroom_s=1.0,
                          max_hold_s=9.0) == 0.0
    assert admission_hold(free=4, queued=0, gap_s=0.1, headroom_s=0.0,
                          max_hold_s=9.0) == 0.0
    # no arrival telemetry yet -> unpredictable -> no hold
    assert admission_hold(free=4, queued=0, gap_s=None, headroom_s=10.0,
                          max_hold_s=2.0) == 0.0


def test_dispatch_hold_decision_table():
    """The dispatch-side kernel: hold a partial stacked batch only while
    outstanding host steps mean more sessions can still join, inside SLO
    headroom."""
    # nothing waiting, or nothing in flight that could join -> release
    assert dispatch_hold(waiting=0, host_in_flight=3, headroom_s=10.0,
                         max_hold_s=1.0) == 0.0
    assert dispatch_hold(waiting=2, host_in_flight=0, headroom_s=10.0,
                         max_hold_s=1.0) == 0.0
    # joinable work in flight -> hold to the cap, inside headroom
    assert dispatch_hold(waiting=2, host_in_flight=1, headroom_s=10.0,
                         max_hold_s=1.0) == 1.0
    assert dispatch_hold(waiting=2, host_in_flight=1, headroom_s=0.4,
                         max_hold_s=1.0) == pytest.approx(0.4)
    # SLO headroom spent -> release immediately
    assert dispatch_hold(waiting=2, host_in_flight=1, headroom_s=0.0,
                         max_hold_s=1.0) == 0.0


def test_planner_holds_from_synthetic_clock():
    """Planner-level hold/release decisions under an injected clock:
    admitted users' SLO ages shrink the headroom until holds release."""
    clock = [0.0]
    cfg = ServeConfig(slo_interactive_s=5.0, slo_batch_s=50.0,
                      max_hold_s=1.0)
    p = AdmissionPlanner(cfg, router=BucketRouter(),
                         clock=lambda: clock[0])
    # inter-arrival telemetry: two enqueues 0.2s apart -> gap EMA 0.2
    p.observe_enqueue(100, t=0.0)
    p.observe_enqueue(100, t=0.2)
    assert p.admission_hold_s(free=4, queued=1) == pytest.approx(0.6)
    # a live interactive user ages: headroom = 5 - age
    p.note_admit("u0", "interactive")
    assert p.window_s(2, 1) == 1.0  # fresh: capped hold
    clock[0] = 4.8
    assert p.window_s(2, 1) == pytest.approx(0.2)  # headroom shrinking
    clock[0] = 5.1
    assert p.window_s(2, 1) == 0.0  # SLO spent: release
    p.note_resolved("u0")
    assert p.window_s(2, 1) == 1.0  # clock stopped constraining
    # hold PERIODS, not consults: the first two holds are one period
    # (no release between), the SLO release ends it, the post-resolve
    # hold starts the second
    assert p.dispatch_hold_rounds == 2 and p.admission_hold_rounds == 1


# -- class-aware queue (pure host) ----------------------------------------


class _E:
    def __init__(self, uid, priority="batch"):
        self.user_id = uid
        self.priority = priority


def test_queue_strict_priority_fifo_within_class():
    q = AdmissionQueue(8)
    for e in (_E("b0"), _E("i0", "interactive"), _E("b1"),
              _E("i1", "interactive")):
        q.put(e)
    assert len(q) == 4
    assert [q.pop()[0].user_id for _ in range(4)] \
        == ["i0", "i1", "b0", "b1"]
    # unknown/missing classes land in the lowest class, never raise
    q.put(_E("x", "warp"))
    q.put("bare-string")
    assert q.pop()[0].user_id == "x"


def test_queue_aging_starvation_guard():
    """The satellite pin: an AGED batch user admits ahead of a fresh
    interactive one — strict priority cannot starve the batch tier."""
    import time as _time

    q = AdmissionQueue(8, aging_s=0.05)
    q.put(_E("b0"))
    q.put(_E("i0", "interactive"))
    assert q.pop()[0].user_id == "i0"  # not aged yet: strict priority
    _time.sleep(0.06)
    q.put(_E("i1", "interactive"))  # fresh interactive arrival
    assert q.pop()[0].user_id == "b0"  # aged batch jumps it
    assert q.pop()[0].user_id == "i1"
    waits = AdmissionQueue(8, aging_s=0.05)
    waits.put(_E("b0"))
    hw = waits.head_waits()
    assert set(hw) == {"batch"} and hw["batch"] >= 0.0


# -- ServeConfig bucket-widths validation (the bugfix satellite) ----------


def test_serve_config_validates_explicit_bucket_widths():
    """Typo'd explicit edges fail at CONSTRUCTION with the reason,
    instead of silently misrouting users to the wrong jit family."""
    assert ServeConfig(bucket_widths=(32, 64)).bucket_widths == (32, 64)
    with pytest.raises(ValueError, match="ascending"):
        ServeConfig(bucket_widths=(64, 32))  # unsorted
    with pytest.raises(ValueError, match="ascending"):
        ServeConfig(bucket_widths=(32, 32, 64))  # duplicate
    with pytest.raises(ValueError, match="positive"):
        ServeConfig(bucket_widths=(0, 32))
    with pytest.raises(ValueError, match="positive"):
        ServeConfig(bucket_widths=(32, -8))
    with pytest.raises(ValueError, match="positive"):
        ServeConfig(bucket_widths=(32.5, 64))  # non-int
    with pytest.raises(ValueError, match="collapse"):
        ServeConfig(bucket_widths=(30, 32))  # both round to 32
    with pytest.raises(ValueError, match="non-empty"):
        validate_bucket_widths(())
    # oversized pools are HANDLED, not an error: pow2 fall-through
    r = BucketRouter((32, 64))
    assert r.width_for(100) == 128
    # planner knob validation rides the same __post_init__
    with pytest.raises(ValueError, match="planner_epoch"):
        ServeConfig(planner_epoch=0)
    with pytest.raises(ValueError, match="SLO"):
        ServeConfig(slo_interactive_s=0.0)
    with pytest.raises(ValueError, match="aging_s"):
        ServeConfig(aging_s=-1.0)


# -- journal-replayed edge determinism (pure host) ------------------------


def test_planner_edges_replay_identically_from_journal(tmp_path):
    """The restart contract, at journal level: a planner rebuilt from a
    replayed journal (last planner record's sketch + the enqueue pool
    sizes after it) derives IDENTICAL edges — including when the kill
    landed between an epoch boundary and its planner append."""
    jp = str(tmp_path / "j.jsonl")
    cfg = ServeConfig(planner_epoch=2)
    pools = [120, 480, 96, 120, 480]
    with AdmissionJournal(jp) as j:
        p = AdmissionPlanner(cfg, router=BucketRouter(), journal=j)
        for i, pool in enumerate(pools):
            j.append("enqueue", f"u{i}", cls="batch", pool=pool)
            p.observe_enqueue(pool, t=float(i))
        live_edges = p.edges
        assert live_edges  # two epochs elapsed
    with AdmissionJournal(jp) as j2:
        r2 = BucketRouter()
        p2 = AdmissionPlanner(cfg, router=r2, journal=j2)
        assert p2.edges == live_edges
        assert r2.widths == live_edges
        assert p2.sketch.n == len(pools)
    # torn planner append: drop the journal's LAST planner record — the
    # replay tail (pool_obs) then re-derives it on restore
    lines = [ln for ln in open(jp).read().splitlines() if ln]
    kept, dropped = [], 0
    for ln in reversed(lines):
        if not dropped and '"planner"' in ln:
            dropped = 1
            continue
        kept.append(ln)
    with open(jp, "w") as f:
        f.write("\n".join(reversed(kept)) + "\n")
    with AdmissionJournal(jp) as j3:
        p3 = AdmissionPlanner(cfg, router=BucketRouter(), journal=j3)
        assert p3.edges == live_edges
        assert p3.sketch.n == len(pools)
    # explicit operator edges WIN: the planner never overrides them
    cfg_explicit = ServeConfig(planner_epoch=2, bucket_widths=(32, 512))
    r4 = BucketRouter((32, 512))
    p4 = AdmissionPlanner(cfg_explicit, router=r4)
    for i, pool in enumerate(pools):
        p4.observe_enqueue(pool, t=float(i))
    assert r4.widths == (32, 512)
    # cross-arm restore: a journal written WITHOUT a planner (pool-
    # carrying enqueues, no planner records) restored by a planner
    # run must append ONE covering record AFTER the whole tail — a
    # mid-restore record would orphan the tail's remainder for the
    # next replay — so a further restart derives identical edges
    jp2 = str(tmp_path / "j2.jsonl")
    with AdmissionJournal(jp2) as j:
        for i, pool in enumerate(pools):
            j.append("enqueue", f"u{i}", cls="batch", pool=pool)
    with AdmissionJournal(jp2) as j:
        p5 = AdmissionPlanner(cfg, router=BucketRouter(), journal=j)
        edges5, n5 = p5.edges, p5.sketch.n
        assert n5 == len(pools) and edges5
    with AdmissionJournal(jp2) as j:
        p6 = AdmissionPlanner(cfg, router=BucketRouter(), journal=j)
        assert (p6.edges, p6.sketch.n) == (edges5, n5)


# -- per-class report surface (pure host) ---------------------------------


def test_report_per_class_latency_histograms():
    report = FleetReport()
    report.admitted("i0", width=32, wait_s=0.0, depth=0, live=1,
                    cls="interactive")
    report.admitted("b0", width=32, wait_s=0.0, depth=0, live=2,
                    cls="batch")
    report.user_done("i0", {"trajectory": []}, {})
    report.user_done("b0", {"trajectory": []}, {})
    s = report.summary(cohort=2)
    per = s["per_class"]
    assert set(per) == {"batch", "interactive"}
    for cls in per:
        assert per[cls]["users"] == 1
        snap = per[cls]["admission_to_finish_s"]
        assert snap["n"] == 1 and snap["p95"] >= 0
    # classes ride the event stream and validate against schema v2
    evs = [e for e in report.events if e["event"] == "admit"]
    assert [e["cls"] for e in evs] == ["interactive", "batch"]
    # the schema tag is stamped at write time (EventWriter.emit)
    assert export.validate_metrics([{"schema": 2, **e}
                                    for e in report.events]) == []


# -- two-class serve smoke (tier-1) ---------------------------------------


def test_slo_serve_two_class_smoke(tmp_path):
    """Planner-on end-to-end: interactive users admit ahead of
    earlier-queued batch users, per-user results match sequential,
    per-class histograms + the planner section land in the summary, the
    planner's derived edges are journaled, and every metrics line
    validates against schema v2."""
    cfg = _cfg(mode="mc", epochs=1)
    specs = [(100, "b0", 30), (101, "i0", 30), (102, "i1", 30)]
    seq, entries = [], []
    for seed, uid, n_songs in specs:
        data = _user_data(seed, uid, n_songs=n_songs)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq.append(ALLoop(cfg).run_user(_committee(data), data, str(p)))
        fp = tmp_path / f"serve_{uid}"
        fp.mkdir()
        entries.append(FleetUser(
            uid, _committee(data), data, str(fp), seed=cfg.seed,
            priority="interactive" if uid.startswith("i") else "batch"))
    jsonl = tmp_path / "fleet_metrics.jsonl"
    report = FleetReport(str(jsonl))
    journal = AdmissionJournal(str(tmp_path / "serve_journal.jsonl"))
    sched = FleetScheduler(cfg, report=report, scoring_by_width=True)
    server = FleetServer(
        sched, ServeConfig(target_live=1, planner_epoch=2),
        journal=journal)
    for e in entries:  # b0 queued FIRST, then the interactive pair
        server.submit(e)
    server.close_intake()
    recs = server.serve(())
    journal.close()
    by = {r["user"]: r for r in recs}
    for s, (_, uid, _) in zip(seq, specs):
        assert by[uid]["error"] is None
        assert by[uid]["result"]["trajectory"] == s["trajectory"]
    # strict priority: both interactive users admitted before the batch
    # user that was queued ahead of them
    admits = [e for e in report.events if e["event"] == "admit"]
    assert [a["user"] for a in admits] == ["i0", "i1", "b0"]
    assert [a["cls"] for a in admits] \
        == ["interactive", "interactive", "batch"]
    summary = report.write_summary(cohort=1)
    assert set(summary["per_class"]) == {"batch", "interactive"}
    assert summary["per_class"]["interactive"]["users"] == 2
    planner = summary["planner"]
    assert planner["edges"] and planner["observations"] == 3
    assert server.planner.edges == tuple(planner["edges"])
    # the journal carries the planner epochs + classes + admit widths:
    # a restarted server re-derives identical routing
    st = AdmissionJournal(str(tmp_path / "serve_journal.jsonl")).state
    assert st.planner_edges == planner["edges"]
    assert st.classes == {"b0": "batch", "i0": "interactive",
                          "i1": "interactive"}
    assert set(st.widths) == {"b0", "i0", "i1"}
    # schema v2, incl. the new cls fields and planner_edges events
    report.close()
    recs2 = export.read_jsonl_tolerant(str(jsonl))
    assert export.validate_metrics(recs2) == []
    assert any(e.get("event") == "planner_edges" for e in recs2)


# -- slow drills ----------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["mc", "hc", "mix", "rand", "wmc"])
def test_slo_planner_parity_host_modes(tmp_path, mode):
    """Per-user parity vs sequential with the planner ON (adaptive
    edges + holds + mixed classes), for every host-committee acquisition
    mode.  Holds change batching, never results."""
    cfg = _cfg(mode=mode, epochs=2)
    specs = [(100, "u0", 30), (101, "u1", 55), (102, "u2", 30)]
    seq, entries = [], []
    for i, (seed, uid, n_songs) in enumerate(specs):
        data = _user_data(seed, uid, n_songs=n_songs)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq.append(ALLoop(cfg).run_user(_committee(data), data, str(p)))
        fp = tmp_path / f"serve_{uid}"
        fp.mkdir()
        entries.append(FleetUser(
            uid, _committee(data), data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp)),
            priority="interactive" if i == 0 else "batch"))
    sched = FleetScheduler(cfg, report=FleetReport(),
                           scoring_by_width=True)
    server = FleetServer(sched,
                         ServeConfig(target_live=2, planner_epoch=2))
    recs = server.serve(iter(entries))
    by = {r["user"]: r for r in recs}
    for s, (_, uid, _) in zip(seq, specs):
        assert by[uid]["error"] is None
        assert by[uid]["result"]["trajectory"] == s["trajectory"]
    assert server.planner.edges  # the planner actually derived edges


@pytest.mark.slow
def test_slo_planner_parity_qbdc(tmp_path):
    """The sixth mode: qbdc (dropout committee on the CNN device path)
    under the planner — bit-identical to its sequential run."""
    from tests.test_acquire import (
        TINY_CNN,
        TINY_TC,
        _cnn_committee,
        _cnn_data,
    )

    cfg = dataclasses.replace(_cfg(mode="qbdc", epochs=2, queries=3),
                              qbdc_k=6)
    specs = [(100, "u0", 8), (101, "u1", 8)]
    seq, entries = [], []
    for i, (seed, uid, n) in enumerate(specs):
        data = _cnn_data(seed, uid, n_songs=n)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq.append(ALLoop(cfg, retrain_epochs=1).run_user(
            _cnn_committee(data), data, str(p)))
        fp = tmp_path / f"serve_{uid}"
        fp.mkdir()
        entries.append(FleetUser(
            uid, _cnn_committee(data), data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp), TINY_CNN, TINY_TC),
            priority="interactive" if i == 0 else "batch"))
    sched = FleetScheduler(cfg, report=FleetReport(),
                           scoring_by_width=True, retrain_epochs=1)
    server = FleetServer(sched,
                         ServeConfig(target_live=2, planner_epoch=2))
    recs = server.serve(iter(entries))
    by = {r["user"]: r for r in recs}
    for s, (_, uid, _) in zip(seq, specs):
        assert by[uid]["error"] is None
        assert by[uid]["result"]["trajectory"] == s["trajectory"]


@pytest.mark.slow
@pytest.mark.faults
def test_slo_planner_restart_identical_edges_classes_results(tmp_path):
    """THE acceptance pin (rides ``scripts/fault_matrix.sh``): a
    SIGKILLed planner-enabled serve run restarts from the journal with
    IDENTICAL bucket edges, class assignments and per-user results.
    The kill lands at the first completion collection — after planner
    epochs derived edges and all users were classed."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(100, "b0", 30), (101, "i0", 30), (102, "b1", 55)]
    seq = []
    for seed, uid, n_songs in specs:
        data = _user_data(seed, uid, n_songs=n_songs)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq.append(ALLoop(cfg).run_user(_committee(data), data, str(p)))

    def entries():
        out = []
        for seed, uid, n_songs in specs:
            data = _user_data(seed, uid, n_songs=n_songs)
            fp = tmp_path / f"serve_{uid}"
            fp.mkdir(exist_ok=True)
            if (fp / "al_state.json").exists():
                committee = workspace.load_committee(str(fp))
            else:
                committee = _committee(data)
            out.append(FleetUser(
                uid, committee, data, str(fp), seed=cfg.seed,
                committee_factory=lambda fp=fp: workspace.load_committee(
                    str(fp)),
                priority="interactive" if uid.startswith("i")
                else "batch"))
        return out

    jpath = str(tmp_path / "serve_journal.jsonl")
    serve_cfg = ServeConfig(target_live=2, planner_epoch=2)
    done: dict = {}
    with faults.inject(FaultRule("serve.collect", "kill", at=1)) as inj:
        journal = AdmissionJournal(jpath)
        sched = FleetScheduler(cfg, report=FleetReport(),
                               scoring_by_width=True)
        server = FleetServer(sched, serve_cfg, journal=journal)
        with pytest.raises(InjectedKill):
            server.serve(iter(entries()),
                         on_result=lambda r: done.update(
                             {r["user"]: r}))
        assert inj.fired
        edges_at_kill = server.planner.edges
        assert edges_at_kill  # epochs elapsed before the kill
        journal.close()

    st = AdmissionJournal(jpath).state
    assert st.planner_edges == list(edges_at_kill)
    classes_at_kill = dict(st.classes)
    widths_at_kill = dict(st.widths)
    assert classes_at_kill == {"b0": "batch", "i0": "interactive",
                               "b1": "batch"}

    journal = AdmissionJournal(jpath)
    assert journal.recovered
    order = journal.state.recovery_order([u for _, u, _ in specs])
    emap = {e.user_id: e for e in entries()}
    for e in emap.values():
        e.priority = "batch"  # journal classes must override, not argv
    report = FleetReport()
    sched = FleetScheduler(cfg, report=report, scoring_by_width=True)
    server = FleetServer(sched, serve_cfg, journal=journal)
    # restored BEFORE the first enqueue: identical edges from replay
    assert server.planner.edges == edges_at_kill
    server.serve(iter(emap[u] for u in order),
                 on_result=lambda r: done.update({r["user"]: r}))
    journal.close()
    for s, (_, uid, _) in zip(seq, specs):
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] == s["trajectory"]
    st = AdmissionJournal(jpath).state
    assert st.finished == {u for _, u, _ in specs}
    # classes and admitted widths preserved across the restart
    assert dict(st.classes) == classes_at_kill
    for u, w in widths_at_kill.items():
        assert st.widths[u] == w
    admits = [e for e in report.events if e["event"] == "admit"]
    assert all(e["cls"] == classes_at_kill[e["user"]] for e in admits)
