"""North-star benchmark: AL pool-scoring wall-clock per iteration.

Measures the fused TPU scoring graph at BASELINE.json configs[4] scale —
16-member committee over a 100k-excerpt synthetic pool — against a CPU
baseline with the reference's structure (``amg_test.py:428-447``): a Python
loop over members, per-frame ``predict_proba``, per-song groupby-mean, then
``np.mean`` → ``scipy.stats.entropy`` → ``argsort`` top-q on host.

Two device implementations of the identical math, both one compiled program:

- **xla**:    batched member logits (one MXU matmul for all members), frame→
  song mean, consensus, entropy, top-k — jit'd, pool axis sharded across all
  available chips (``ops.scoring`` + einsum).  This is the production path
  and what ``--impl auto`` (the default) runs.
- **pallas**: the same chain as ONE hand-fused Pallas kernel
  (``experimental.pallas_scoring``) — opt-in via ``--impl pallas``: the op
  is HBM-bound and XLA's fusion already ties the hand kernel at north-star
  scale while compiling ~7x faster (see ``experimental/__init__.py``).

Timing methodology: the per-iteration body is chained *inside the compiled
program* (``lax.fori_loop``, iterations linked through a scalar data
dependency) and one host sync closes each window.  On this environment's
tunneled TPU a single dispatch costs ~2 ms and a host readback ~90 ms —
per-call timing would measure the tunnel, not the device; a real AL loop
consuming device-resident results pays neither.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
``vs_baseline`` is the CPU-over-device speedup (higher is better; the
BASELINE.json north star is >= 50x).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _provenance() -> dict:
    """Platform / device-count / commit fields stamped into every bench JSON
    line so cross-round artifacts (BENCH_*_r{N}.json) are comparable."""
    import os
    import subprocess

    import jax

    devs = jax.devices()
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__))).stdout.strip() \
            or None
    except Exception:
        commit = None
    return {"platform": devs[0].platform, "device_kind": devs[0].device_kind,
            "n_devices": len(devs), "commit": commit}


def make_inputs(n_members: int, n_pool: int, n_frames: int, n_features: int,
                n_class: int, seed: int = 1987):
    """Synthetic pool features + linear committee members.

    Frame features mirror the AMG openSMILE layout (260-d per-second frames,
    several frames per song — ``amg_test.py:64,435-437``); members are
    softmax-linear probabilistic classifiers (the SGD-logistic committee
    member's functional form).
    """
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n_pool, n_frames, n_features), np.float32)
    w = (rng.standard_normal((n_members, n_features, n_class), np.float32)
         / np.sqrt(n_features))
    b = rng.standard_normal((n_members, n_class), np.float32) * 0.1
    return x, w, b


def make_hc_table(n_pool: int, n_class: int, seed: int = 2021) -> np.ndarray:
    """Synthetic human-consensus frequency table: per-song annotator
    quadrant frequencies rounded to 3 decimals (``amg_test.py:109-117``)."""
    rng = np.random.default_rng(seed)
    counts = rng.integers(0, 20, size=(n_pool, n_class)).astype(np.float64)
    counts[:, 0] += 1  # every song has at least one annotator
    freq = counts / counts.sum(axis=1, keepdims=True)
    return np.round(freq, 3).astype(np.float32)


def cpu_reference_iteration(x, w, b, k: int, mode: str = "mc",
                            hc_freq=None):
    """Reference-structure scoring on host for one acquisition iteration.

    mc  (``amg_test.py:428-447``): per-member Python loop, per-frame
        ``predict_proba``, per-song groupby-mean, consensus mean → scipy
        entropy → argsort top-q.
    hc  (``amg_test.py:449-455``): scipy entropy over the HC frequency rows.
    mix (``amg_test.py:457-484``): mc consensus rows stacked with the HC
        rows (``pd.concat``), entropy over all rows, top-q in the stacked
        row space.
    """
    from scipy.stats import entropy as scipy_entropy

    if mode == "hc":
        ent = scipy_entropy(hc_freq.astype(np.float64), axis=1)
        return ent, np.argsort(ent)[::-1][:k]

    n_pool, n_frames, n_features = x.shape
    frames = x.reshape(n_pool * n_frames, n_features)
    pred_prob = []
    for m in range(w.shape[0]):  # sequential member loop, as the reference
        logits = frames @ w[m] + b[m]
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(axis=1, keepdims=True)
        # groupby('s_id').mean() — frames are contiguous per song here.
        pred_prob.append(p.reshape(n_pool, n_frames, -1).mean(axis=1))
    consensus = np.mean(np.asarray(pred_prob), axis=0)
    if mode == "mix":
        consensus = np.concatenate([consensus, hc_freq.astype(np.float64)])
    ent = scipy_entropy(consensus, axis=1)
    q_idx = np.argsort(ent)[::-1][:k]
    return ent, q_idx


def build_xla_impl(x, w, b, k: int, mode: str = "mc", hc_freq=None,
                   flat_gemm: bool = False):
    """jit'd einsum → fused scorer, pool axis sharded across all devices.

    Returns ``(iteration_args, iteration_fn)`` where ``iteration_fn(args,
    eps)`` -> ScoreResult; ``eps`` is a scalar folded in as a no-op so timing
    windows can chain iterations through a device-side data dependency.

    ``mode`` picks the acquisition chain (mc / hc / mix — BASELINE configs
    0-2).  ``flat_gemm`` races an alternative mc layout: one
    ``(N*K, F) @ (F, M*C)`` GEMM instead of the batched member einsum —
    identical math, different XLA tiling.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from consensus_entropy_tpu.ops.scoring import score_mc, score_mix
    from consensus_entropy_tpu.parallel.mesh import POOL_AXIS, make_pool_mesh

    mesh = make_pool_mesh()
    n_pool = hc_freq.shape[0] if mode == "hc" else x.shape[0]
    n_dev = mesh.devices.size
    n_pad = -(-n_pool // n_dev) * n_dev
    mask = np.zeros(n_pad, bool)
    mask[:n_pool] = True

    x_sh = NamedSharding(mesh, P(POOL_AXIS))

    if hc_freq is not None:
        hc_pad = np.zeros((n_pad, hc_freq.shape[1]), np.float32)
        hc_pad[:n_pool] = hc_freq

    if mode == "hc":  # no member inputs in the loop — x/w/b never touched
        # PRODUCTION semantics (al/acquisition.py): the hc table's row
        # entropies are loop-invariant, computed once at acquirer
        # construction; the per-iteration device work is the masked top-k
        # over the precomputed (N,) entropy vector.  The CPU baseline
        # keeps the reference's actual per-iteration work (scipy entropy
        # + argsort every iteration, amg_test.py:449-455) — outputs are
        # identical, the hoisting is the framework's win.
        from consensus_entropy_tpu.ops.entropy import shannon_entropy
        from consensus_entropy_tpu.ops.scoring import score_hc_precomputed

        hc_ent = jax.jit(shannon_entropy)(jax.device_put(hc_pad, x_sh))
        args = (hc_ent, jax.device_put(mask, x_sh))

        def iteration(args, eps):
            ent, hmask = args
            return score_hc_precomputed(ent + eps * 0.0, hmask, k=k)

        return args, iteration

    x_pad = np.zeros((n_pad,) + x.shape[1:], np.float32)
    x_pad[:n_pool] = x
    args = (jax.device_put(x_pad, x_sh), jnp.asarray(w), jnp.asarray(b),
            jax.device_put(mask, x_sh))
    if mode == "mix":
        args = args + (jax.device_put(hc_pad, x_sh),)

    # Measured and rejected: a lax.map-over-pool-chunks variant (reusing
    # per-chunk intermediates instead of materializing (M, N, K, C)) ran
    # 6.1 ms/iter vs 1.4 for the einsum at north-star scale — the
    # sequential map defeats XLA's cross-chunk pipelining, and the fused
    # einsum chain is already closer to the HBM floor than the
    # materialization argument assumed.
    def member_song_probs(x, w, b):
        if flat_gemm:
            n, kf, f = x.shape
            m, _, c = w.shape
            w_flat = jnp.transpose(w, (1, 0, 2)).reshape(f, m * c)
            logits = (x.reshape(n * kf, f) @ w_flat).reshape(n, kf, m, c)
            logits = logits + b[None, None]
            probs = jax.nn.softmax(logits, axis=-1)
            return jnp.transpose(jnp.mean(probs, axis=1), (1, 0, 2))
        logits = jnp.einsum("nkf,mfc->mnkc", x, w)
        logits = logits + b[:, None, None, :]
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.mean(probs, axis=2)  # groupby(s_id).mean() parity

    if mode == "mix":

        def iteration(args, eps):
            x, w, b, mask, hc = args
            song_probs = member_song_probs(x, w + eps * 0.0, b)
            return score_mix(song_probs, mask, hc, mask, k=k)

    else:

        def iteration(args, eps):
            x, w, b, mask = args
            song_probs = member_song_probs(x, w + eps * 0.0, b)
            return score_mc(song_probs, mask, k=k)

    return args, iteration


def build_pallas_impl(x, w, b, k: int, tile_n: int, fuse_topk: bool = False):
    """Pre-packed pool + the hand-fused Pallas kernel.  On a single chip the
    kernel runs directly; on a multi-chip mesh it runs per pool shard under
    ``shard_map`` with an O(k·D) candidate merge
    (``parallel.sharding.make_shardmap_pallas_mc_scorer``).  Frames are
    lane-packed (``auto_pack``) so every matmul/VPU op fills the full
    128-lane vreg."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from consensus_entropy_tpu.experimental.pallas_scoring import (
        auto_pack,
        pack_pool,
        pack_weights,
        packed_score_mc,
    )
    from consensus_entropy_tpu.ops.scoring import ScoreResult
    from consensus_entropy_tpu.parallel.mesh import POOL_AXIS, make_pool_mesh
    from consensus_entropy_tpu.parallel.sharding import (
        make_shardmap_pallas_mc_scorer,
    )

    n_members, n_pool = w.shape[0], x.shape[0]
    n_frames, n_class = x.shape[1], w.shape[2]
    n_dev = len(jax.devices())
    pack = auto_pack(n_frames, n_members, n_class)
    x_tiles, _ = pack_pool(x, tile_n, pack)
    w_p, b_p = pack_weights(w, b, pack)
    n_eff = n_members * pack
    # Pad the tile axis to a device multiple (padding tiles are all-masked).
    n_tiles = x_tiles.shape[0]
    n_tiles_pad = -(-n_tiles // n_dev) * n_dev
    if n_tiles_pad != n_tiles:
        x_tiles = np.pad(np.asarray(x_tiles),
                         ((0, n_tiles_pad - n_tiles),) + ((0, 0),) * 3)
    _log(f"[pallas] frame packing x{pack}: {n_eff * n_class} lanes, "
         f"{n_frames // pack} matmuls/tile, tile_n={tile_n}, "
         f"{n_tiles_pad} tiles / {n_dev} device(s)")
    n_rows = n_tiles_pad * tile_n
    mask = np.zeros(n_rows, bool)
    mask[:n_pool] = True

    if n_dev == 1:
        args = (jax.device_put(jnp.asarray(x_tiles)), jnp.asarray(w_p),
                jnp.asarray(b_p), jnp.asarray(mask))

        def iteration(args, eps):
            x_tiles, w_packed, b_packed, mask = args
            ent, values, indices = packed_score_mc(
                x_tiles, w_packed + eps * 0.0, b_packed, mask,
                n_members=n_eff, k=k, fuse_topk=fuse_topk)
            return ScoreResult(ent, values, indices)
    else:
        mesh = make_pool_mesh()
        tiles_s = NamedSharding(mesh, P(POOL_AXIS, None, None, None))
        rows_s = NamedSharding(mesh, P(POOL_AXIS))
        repl = NamedSharding(mesh, P())
        args = (jax.device_put(jnp.asarray(x_tiles), tiles_s),
                jax.device_put(jnp.asarray(w_p), repl),
                jax.device_put(jnp.asarray(b_p), repl),
                jax.device_put(jnp.asarray(mask), rows_s))
        scorer = make_shardmap_pallas_mc_scorer(mesh, n_members=n_eff, k=k,
                                                fuse_topk=fuse_topk)

        def iteration(args, eps):
            x_tiles, w_packed, b_packed, mask = args
            return scorer(x_tiles, w_packed + eps * 0.0, b_packed, mask)

    return args, iteration


def failure_message(e: BaseException) -> str:
    """First AND last non-empty lines of an error, bounded: compile errors
    bury the root cause (VMEM overflow, etc.) below a transport wrapper
    (the axon tunnel surfaces server-side compile failures as an opaque
    HTTP-500 first line), so neither line alone substantiates the
    committed ``impl_failures`` entry."""
    lines = [ln for ln in str(e).split("\n") if ln.strip()]
    # bound each line SEPARATELY: one overlong transport wrapper must not
    # truncate away the root-cause tail this helper exists to preserve
    msg = (lines[0] if lines else repr(e))[:250]
    if len(lines) > 1 and lines[-1] != lines[0]:
        msg += " | " + lines[-1][:250]
    return msg


def time_device_impl(name: str, args, iteration, *, chain: int, trials: int):
    """Median per-iteration latency of ``iteration`` chained ``chain`` times
    inside one compiled program (one dispatch + one sync per window)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def window(args, eps):
        return lax.fori_loop(
            0, chain, lambda i, e: iteration(args, e).values[0] * 1e-12, eps)

    t0 = time.perf_counter()
    out = window(args, jnp.float32(0.0))
    np.asarray(out)
    _log(f"[{name}] compile + first window: {time.perf_counter() - t0:.2f}s")

    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        out = window(args, jnp.float32(0.0))
        np.asarray(out)  # one sync per chain
        times.append((time.perf_counter() - t0) / chain)
    ms = float(np.median(times) * 1e3)
    _log(f"[{name}] median over {trials} x {chain}-iter windows: "
         f"{ms:.3f} ms/iter (min {min(times) * 1e3:.3f})")
    return ms


def check_parity(name: str, args, iteration, ent_cpu, idx_cpu, k: int,
                 tol: float = 1e-3, n_valid: int | None = None) -> bool:
    """One un-chained evaluation vs the float64 CPU oracle.

    The query-set check is boundary-tolerant: when the oracle's rank-k gap
    is below float32 resolution (on this synthetic pool the top ranks sit
    ~1e-6 apart at entropy ≈ ln 4), no f32 implementation can reproduce the
    float64 set exactly and the order of two near-ties is rounding luck.
    The principled contract is: every selected song scores within ``tol`` of
    the oracle's k-th-best, and every song clearly above the boundary
    (> kth + tol) is selected.

    ``n_valid``: unpadded pool width.  For the mix mode the oracle row space
    is ``[consensus (n_valid); hc (n_valid)]`` while the device rows are
    ``[consensus (n_pad); hc (n_pad)]`` — rows/indices are remapped before
    comparison.
    """
    import jax.numpy as jnp

    result = iteration(args, jnp.float32(0.0))
    ent_dev_all = np.asarray(result.entropy)
    idx_dev = np.asarray(result.indices)
    n_pool = ent_cpu.shape[0]
    if n_valid is not None and n_pool == 2 * n_valid:  # mix: stacked rows
        n_pad = ent_dev_all.shape[0] // 2
        ent_dev = np.concatenate([ent_dev_all[:n_valid],
                                  ent_dev_all[n_pad: n_pad + n_valid]])
        idx_dev = np.where(idx_dev >= n_pad,
                           idx_dev - n_pad + n_valid, idx_dev)
    else:
        ent_dev = ent_dev_all[:n_pool]
    max_err = float(np.max(np.abs(ent_dev - ent_cpu)))
    kth = np.sort(ent_cpu)[-k]
    distinct = len(set(idx_dev.tolist())) == k
    all_near_top = bool(np.all(ent_cpu[idx_dev] >= kth - tol))
    must_have = np.flatnonzero(ent_cpu > kth + tol)
    clear_winners_in = set(must_have.tolist()) <= set(idx_dev.tolist())
    ok = (max_err <= tol and distinct and all_near_top and clear_winners_in)
    _log(f"[{name}] entropy max |err| vs scipy: {max_err:.2e}; top-{k} "
         f"boundary-consistent: {all_near_top and clear_winners_in} "
         f"(exact-set match: "
         f"{set(idx_dev.tolist()) == set(idx_cpu.tolist())})")
    return ok


def run_cnn_suite(args_ns) -> int:
    """BASELINE configs[3] evidence: the Flax ShortChunkCNN committee at the
    full reference geometry (59049-sample crops, 128 mels, 7 conv blocks)
    scoring a pool of crops — all members in ONE vmap'd program vs the
    reference's sequential member loop at batch_size=1
    (``amg_test.py:428-434`` structure, here on jax-CPU instead of torch).
    The CPU loop scores a small subpool and is scaled linearly (logged)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from consensus_entropy_tpu.config import CNNConfig
    from consensus_entropy_tpu.models import short_cnn

    import dataclasses

    config = CNNConfig(arch=args_ns.arch)
    n_members, n_songs = args_ns.members, args_ns.pool
    rng = np.random.default_rng(1987)
    # class-correlated tone crops (not pure noise): trained members then
    # see in-distribution inputs, so the bf16 gate measures the error
    # regime production scoring actually runs in (saturated sigmoids),
    # not noise-scoring tie-breaks.  Timing is content-independent.
    from consensus_entropy_tpu.al.evidence import TONE_FREQS

    classes = rng.integers(0, 4, n_songs)
    t_axis = np.arange(config.input_length) / config.sample_rate
    tone_f = np.asarray(TONE_FREQS)  # one source of class-tone geometry
    crops = (np.sin(2 * np.pi * tone_f[classes][:, None] * t_axis)
             + 0.3 * rng.standard_normal(
                 (n_songs, config.input_length))).astype(np.float32)
    members = [short_cnn.init_variables(jax.random.key(i), config)
               for i in range(n_members)]
    _log(f"devices: {jax.devices()}")
    _log(f"cnn committee: {n_members} members x {n_songs} crops of "
         f"{config.input_length} samples")
    if args_ns.gate_weights == "trained":
        # Brief full-geometry training (round-2/3 ADVICE: the bf16 parity
        # gate must be evaluated on TRAINED weights, not random init):
        # fit_many on the tone crops drives the sigmoid heads into their
        # saturated production regime; same trunk geometry as the timed op.
        from consensus_entropy_tpu.config import TrainConfig
        from consensus_entropy_tpu.data.audio import DeviceWaveformStore
        from consensus_entropy_tpu.labels import one_hot_np
        from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer

        ids = [f"s{i}" for i in range(n_songs)]
        store = DeviceWaveformStore(dict(zip(ids, crops)),
                                    config.input_length)
        y1 = one_hot_np(classes)
        trainer = CNNTrainer(config, TrainConfig(batch_size=5, lr=1e-3))
        t0 = time.perf_counter()
        members, _ = trainer.fit_many(
            members, store, ids, y1, ids, y1, jax.random.key(7),
            n_epochs=args_ns.gate_train_epochs)
        _log(f"[gate] trained {n_members} members x "
             f"{args_ns.gate_train_epochs} epochs on the tone pool in "
             f"{time.perf_counter() - t0:.1f}s")
    stacked = short_cnn.stack_params(members)

    def make_window(cfg):
        def iteration(stacked, crops, eps):
            return short_cnn.committee_infer(
                jax.tree.map(lambda a: a + eps * 0.0, stacked), crops, cfg)

        @jax.jit
        def window(stacked, crops, eps):
            return lax.fori_loop(
                0, args_ns.chain,
                lambda i, e: jnp.mean(iteration(stacked, crops, e)) * 1e-12,
                eps)

        return iteration, window

    def time_dtype(tag, cfg, sd, cd):
        iteration, window = make_window(cfg)
        t0 = time.perf_counter()
        np.asarray(window(sd, cd, jnp.float32(0.0)))
        _log(f"[tpu:{tag}] compile + first window: "
             f"{time.perf_counter() - t0:.1f}s")
        times = []
        for _ in range(args_ns.trials):
            t0 = time.perf_counter()
            np.asarray(window(sd, cd, jnp.float32(0.0)))
            times.append((time.perf_counter() - t0) / args_ns.chain)
        ms = float(np.median(times) * 1e3)
        _log(f"[tpu:{tag}] {ms:.2f} ms per committee-x-pool scoring pass "
             f"({n_members * n_songs / ms * 1e3:.0f} member-crops/s)")
        return ms, iteration

    sd = jax.device_put(stacked)
    cd = jnp.asarray(crops)
    dev_ms, it_f32 = time_dtype("f32", config, sd, cd)
    # race bfloat16 compute (params/stats stay f32 — models/short_cnn.py);
    # convs dominate this op, so the MXU's native bf16 path is the candidate
    bf16_cfg = dataclasses.replace(config, compute_dtype="bfloat16")
    bf16_ms, it_bf16 = time_dtype("bf16", bf16_cfg, sd, cd)
    p32 = np.asarray(jax.jit(it_f32)(sd, cd, jnp.float32(0.0)))
    p16 = np.asarray(jax.jit(it_bf16)(sd, cd, jnp.float32(0.0)))
    bf16_err = float(np.max(np.abs(p32 - p16)))
    # Gate on probability tolerance alone.  Top-1 agreement is context:
    # meaningful on trained members (saturated sigmoids, the default gate
    # path), but on --gate-weights random it is a tie-break of near-0.5
    # sigmoids that would flip nondeterministically — so it is logged,
    # not gated.
    agree = float((p32.argmax(-1) == p16.argmax(-1)).mean())
    _log(f"[bf16] max |prob err| vs f32: {bf16_err:.2e}; "
         f"top-1 agreement (informational): {agree:.3f}")
    winner = "float32"
    if bf16_ms < dev_ms and bf16_err <= 0.02:
        _log(f"[bf16] wins ({bf16_ms:.2f} vs {dev_ms:.2f} ms) within the "
             f"probability-parity gate")
        dev_ms = bf16_ms
        winner = "bfloat16"

    # CPU: reference structure — per-member Python loop, batch_size=1.
    n_cpu = min(4, n_songs)
    cpu_dev = jax.devices("cpu")[0]
    with jax.default_device(cpu_dev):
        cpu_stacked = jax.device_put(stacked, cpu_dev)
        one = jax.jit(lambda v, x: short_cnn.apply_infer(v, x, config))
        # warm up trace+compile outside the timed window (device path does
        # the same at its first-window call)
        np.asarray(one(short_cnn.unstack_params(cpu_stacked, 0),
                       crops[0:1]))
        t0 = time.perf_counter()
        for m in range(n_members):
            member = short_cnn.unstack_params(cpu_stacked, m)
            for j in range(n_cpu):
                np.asarray(one(member, crops[j: j + 1]))
        cpu_elapsed = time.perf_counter() - t0
    cpu_ms = cpu_elapsed * (n_songs / n_cpu) * 1e3
    _log(f"[cpu] member-loop batch-1 on {n_cpu}/{n_songs} songs: "
         f"{cpu_elapsed * 1e3:.0f} ms -> {cpu_ms:.0f} ms extrapolated "
         f"linearly to the full pool")

    # Roofline/MFU accounting from XLA's OWN cost model on the compiled
    # winning-dtype program (round-4 VERDICT: the README's prose roofline
    # applied f32 byte accounting to a bf16 run and claimed a floor ABOVE
    # the measured time — impossible; the artifact, not prose, now carries
    # dtype-correct numbers).  cost_analysis() reflects the optimized
    # post-fusion HLO, so fused elementwise traffic isn't double-counted.
    roofline = None
    try:
        it_win = it_bf16 if winner == "bfloat16" else it_f32
        ca = (jax.jit(it_win).lower(sd, cd, jnp.float32(0.0))
              .compile().cost_analysis())
        if isinstance(ca, list):  # older jax returns [dict]
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        gbytes = float(ca.get("bytes accessed", 0.0)) / 1e9
        roofline = {
            "source": "XLA cost_analysis on the compiled "
                      f"{winner} program",
            "flops_G": round(flops / 1e9, 1),
            "bytes_accessed_GB": round(gbytes, 3),
        }
        dev0 = jax.devices()[0]
        # Peak constants are DEVICE-SPECIFIC; only v5e's are known here.
        # Emitting v5e floors from another chip (or the CPU validation
        # backend) would be exactly the mismatched-accounting error this
        # block exists to prevent, so floor/MFU attach only on v5 lite.
        if dev0.platform == "tpu" and "v5 lite" in dev0.device_kind \
                and gbytes > 0 and flops > 0:
            # v5e: 197 TFLOP/s bf16 peak, ~819 GB/s HBM.  MFU is always
            # quoted against the bf16 peak — the hardware maximum — so an
            # f32 winner reads as a lower fraction rather than flattering
            # itself against a softer denominator.
            peak_tf, hbm_gbps = 197.0, 819.0
            floor_ms = gbytes / hbm_gbps * 1e3
            roofline.update({
                "peaks_device": dev0.device_kind,
                "hbm_GBps_peak": hbm_gbps,
                "peak_tflops_bf16": peak_tf,
                "hbm_roofline_floor_ms": round(floor_ms, 2),
                "measured_over_floor": round(dev_ms / floor_ms, 2),
                "mfu": round(flops / (dev_ms * 1e-3) / (peak_tf * 1e12),
                             3),
            })
            _log(f"[roofline] {gbytes:.2f} GB accessed -> "
                 f"{floor_ms:.2f} ms HBM floor; measured {dev_ms:.2f} ms "
                 f"({dev_ms / floor_ms:.2f}x floor), "
                 f"MFU {roofline['mfu']:.1%} of {peak_tf:.0f} TF/s bf16")
        else:
            _log(f"[roofline] cost model only ({gbytes:.2f} GB, "
                 f"{flops / 1e9:.1f} GFLOP): no peak constants for "
                 f"{dev0.platform}/{dev0.device_kind}")
    except Exception as e:  # cost model unavailable on some backends
        roofline = None
        _log(f"[roofline] cost_analysis unavailable: {e}")

    print(json.dumps({
        "metric": (f"cnn_committee_scoring_{n_members}m_{n_songs}"
                   + ("" if args_ns.arch == "vgg" else f"_{args_ns.arch}")),
        "dtype": winner,
        # trained: members fit_many-trained on the tone pool before gating
        # (the production error regime); random_init: quick-run fallback
        "bf16_gate": f"prob_tol_0.02_{args_ns.gate_weights}",
        "bf16_max_prob_err": round(bf16_err, 6),
        "bf16_top1_agreement": round(agree, 4),
        "roofline": roofline,
        "value": round(dev_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / dev_ms, 1),
        **_provenance(),
    }))
    return 0


def run_retrain_suite(args_ns) -> int:
    """Committee CNN retraining: ONE lockstep jit per epoch for all M members
    (``CNNTrainer.fit_many``) vs the sequential per-member loop the reference
    runs (``amg_test.py:496-502``, hot loop #2).  Reports the lockstep
    per-epoch latency; ``vs_baseline`` is sequential/lockstep total wall-clock
    — the factor by which per-iteration retraining stops scaling in M.

    Also races mixed-precision training (``compute_dtype='bfloat16'``: bf16
    convs, f32 params/optimizer/loss) against f32 in the SAME process —
    absolute timings on the tunneled chip drift run-to-run, so only the
    in-process ratio is meaningful.  bf16 becomes the headline only when its
    training trajectory stays sane (finite, train loss decreasing); the
    QUALITY equivalence gate on a separable task lives in
    ``tests/test_cnn_trainer.py::test_bf16_training_quality_parity``.
    """
    import dataclasses

    import jax

    from consensus_entropy_tpu.config import CNNConfig, TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.models import short_cnn
    from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer

    config = CNNConfig()
    n_members = 5 if args_ns.members is None else args_ns.members
    n_epochs = args_ns.retrain_epochs
    q, n_test = 10, 4  # the reference query batch (-q 10) + a small test set
    rng = np.random.default_rng(1987)
    waves = {f"s{i}": (rng.standard_normal(70000) * 0.05).astype(np.float32)
             for i in range(q + n_test)}
    store = DeviceWaveformStore(waves, config.input_length)
    ids = list(waves)
    train_ids, test_ids = ids[:q], ids[q:]
    y_tr = np.eye(4, dtype=np.float32)[rng.integers(0, 4, q)]
    y_te = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n_test)]
    members = [short_cnn.init_variables(jax.random.key(i), config)
               for i in range(n_members)]
    _log(f"devices: {jax.devices()}")
    _log(f"retrain: {n_members} members x {n_epochs} epochs on q={q} songs "
         f"(full {config.input_length}-sample geometry)")

    def copies():
        return [jax.tree.map(lambda a: a.copy(), v) for v in members]

    key = jax.random.key(7)
    trainer = CNNTrainer(config, TrainConfig())
    # warm-up OUTSIDE the timed windows, at the SAME n_epochs as the timed
    # runs: the callback-free fit_many path scans whole schedule phases and
    # its program cache keys on the segment length, so an n_epochs=1
    # warm-up would leave every timed phase program compiling in-window
    trainer.fit(copies()[0], store, train_ids, y_tr, test_ids, y_te, key,
                n_epochs=n_epochs)
    trainer.fit_many(copies(), store, train_ids, y_tr, test_ids, y_te, key,
                     n_epochs=n_epochs)

    t0 = time.perf_counter()
    for i, v in enumerate(copies()):
        trainer.fit(v, store, train_ids, y_tr, test_ids, y_te,
                    jax.random.fold_in(key, i), n_epochs=n_epochs)
    seq_s = time.perf_counter() - t0
    _log(f"[sequential] {n_members} fit loops: {seq_s * 1e3:.0f} ms "
         f"({seq_s / n_epochs / n_members * 1e3:.1f} ms/member-epoch)")

    t0 = time.perf_counter()
    _, hist32 = trainer.fit_many(copies(), store, train_ids, y_tr, test_ids,
                                 y_te, key, n_epochs=n_epochs)
    vmap_s = time.perf_counter() - t0
    ms_epoch = vmap_s / n_epochs * 1e3
    _log(f"[lockstep f32] one loop: {vmap_s * 1e3:.0f} ms "
         f"({ms_epoch:.1f} ms/epoch for all {n_members} members)")

    # race mixed-precision training (params/opt stay f32; convs in bf16)
    bf16_cfg = dataclasses.replace(config, compute_dtype="bfloat16")
    bf16_trainer = CNNTrainer(bf16_cfg, TrainConfig())
    # warm-up at the timed n_epochs (scanned-phase cache keys on length)
    bf16_trainer.fit_many(copies(), store, train_ids, y_tr, test_ids, y_te,
                          key, n_epochs=n_epochs)
    t0 = time.perf_counter()
    _, hist16 = bf16_trainer.fit_many(copies(), store, train_ids, y_tr,
                                      test_ids, y_te, key, n_epochs=n_epochs)
    bf16_s = time.perf_counter() - t0
    bf16_ms = bf16_s / n_epochs * 1e3
    l32 = np.array([h[-1]["train_loss"] for h in hist32])
    l16 = np.array([h[-1]["train_loss"] for h in hist16])
    sane = (np.all(np.isfinite(l16))
            and np.mean(l16) <= np.mean(
                [h[0]["train_loss"] for h in hist16]))
    _log(f"[lockstep bf16] {bf16_s * 1e3:.0f} ms ({bf16_ms:.1f} ms/epoch); "
         f"final train loss f32 {np.mean(l32):.4f} vs bf16 "
         f"{np.mean(l16):.4f}; trajectory sane: {sane}")
    dtype = "float32"
    if bf16_ms < ms_epoch and sane:
        _log(f"[bf16] wins ({bf16_ms:.1f} vs {ms_epoch:.1f} ms/epoch, "
             f"{ms_epoch / bf16_ms:.2f}x)")
        ms_epoch = bf16_ms
        dtype = "bfloat16"

    print(json.dumps({
        "metric": f"cnn_committee_retrain_epoch_{n_members}m_q{q}",
        "dtype": dtype,
        "value": round(ms_epoch, 3),
        "unit": "ms",
        # vs_baseline stays the f32-vs-f32 lockstep-scaling factor — the
        # dtype race only affects the headline value/dtype fields, so the
        # ratio compares the same quantity across machines
        "vs_baseline": round(seq_s / vmap_s, 2),
        **_provenance(),
    }))
    return 0


def _sized_fleet_workload(sizes: list[int], n_feat: int, seed: int,
                          sgd1_names: list | None = None):
    """Synthetic multi-user AL workload: class-separable per-user song
    pools (``sizes[u]`` songs for user u) + a fresh 3-member host
    committee per run (GNB + 2 SGD — the paper's partial_fit species),
    mirroring the AMG per-user shape.  Returns
    ``[(UserData, committee_factory), ...]``; the factory builds an
    identical fresh committee each call so sequential, fleet and serve
    runs start from the same state.  ``sgd1_names[u]`` overrides user u's
    second SGD member name (the serve-faults suite gives flaky users
    uniquely-named victims so member-filtered fault rules hit per
    user)."""
    from consensus_entropy_tpu.al.loop import UserData
    from consensus_entropy_tpu.models.committee import Committee, FramePool
    from consensus_entropy_tpu.models.sklearn_members import (
        GNBMember,
        SGDMember,
    )

    users = []
    for u, n_songs in enumerate(sizes):
        rng = np.random.default_rng(seed + u)
        centers = rng.standard_normal((4, n_feat)).astype(np.float32) * 2.5
        rows, sids, labels = [], [], {}
        for i in range(n_songs):
            sid = f"song{i:03d}"
            c = int(rng.integers(0, 4))
            labels[sid] = c
            k = int(rng.integers(4, 9))
            rows.append(centers[c] + rng.standard_normal(
                (k, n_feat)).astype(np.float32))
            sids += [sid] * k
        pool = FramePool(np.vstack(rows), sids)
        counts = rng.integers(1, 30, size=(n_songs, 4))
        hc = np.round(counts / counts.sum(1, keepdims=True),
                      3).astype(np.float32)
        data = UserData(f"u{u}", pool, labels, hc_rows=hc)
        X = pool.X
        y = np.array([labels[s] for s in np.repeat(
            pool.song_ids, pool.counts)], np.int32)

        sgd1 = sgd1_names[u] if sgd1_names else "sgd.it_1"

        def factory(X=X, y=y, sgd1=sgd1):
            return Committee([GNBMember("gnb.it_0").fit(X, y),
                              SGDMember("sgd.it_0", seed=0).fit(X, y),
                              SGDMember(sgd1, seed=1).fit(X, y)], [])

        users.append((data, factory))
    return users


def _fleet_workload(n_users: int, n_songs: int, n_feat: int, seed: int):
    """Uniform-size workload (the fleet suite's shape)."""
    return _sized_fleet_workload([n_songs] * n_users, n_feat, seed)


def run_fleet_suite(args_ns) -> int:
    """Fleet engine throughput: users/sec of ``--fleet N`` concurrent AL
    sessions (``fleet.FleetScheduler`` — one vmapped scoring dispatch per
    phase-aligned cohort, host retraining on a worker pool) vs the
    sequential ``ALLoop.run_user`` loop over the IDENTICAL synthetic
    workload and seeds.  Parity is asserted (per-user trajectories must
    match the sequential run exactly) so the speedup is for the same
    results, then one BENCH line records users/sec + occupancy per N.
    """
    import os
    import shutil
    import tempfile

    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.config import ALConfig
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, \
        FleetUser

    cfg = ALConfig(queries=args_ns.k, epochs=args_ns.al_epochs, mode="mc",
                   seed=1987, ckpt_dtype="float32")
    n_users = args_ns.users
    users = _fleet_workload(n_users, args_ns.pool or 150, 96, cfg.seed)
    _log(f"fleet workload: {n_users} users x {args_ns.pool or 150} songs, "
         f"3 host members, q={cfg.queries}, {cfg.epochs} AL iterations")

    root = tempfile.mkdtemp(prefix="fleet_bench_")
    reps = args_ns.reps
    sweep_ns = sorted(set(args_ns.fleet))
    try:
        # Timing reps are INTERLEAVED (seq, then each fleet N, per rep)
        # and each side reports its best (min-wall) rep: this image's cpu
        # shares are throttled, so sustained load slows over a run and a
        # sequentially-ordered comparison hands whichever side ran first
        # a systematic edge.  Parity is checked on every rep.
        loop = ALLoop(cfg)
        seq_results = None
        seq_s = float("inf")
        sweep = {}
        for rep in range(reps):
            t0 = time.perf_counter()
            results = []
            for i, (data, factory) in enumerate(users):
                p = os.path.join(root, f"seq{rep}_{i}")
                os.makedirs(p)
                results.append(loop.run_user(factory(), data, p,
                                             seed=cfg.seed))
            seq_s = min(seq_s, time.perf_counter() - t0)
            if seq_results is None:
                seq_results = results
            elif [r["trajectory"] for r in results] \
                    != [r["trajectory"] for r in seq_results]:
                raise AssertionError("sequential reps diverged")

            for n in sweep_ns:
                report = FleetReport()
                sched = FleetScheduler(cfg, report=report,
                                       host_workers=args_ns.host_workers,
                                       user_timings=False)
                t0 = time.perf_counter()
                recs = []
                for lo in range(0, n_users, n):
                    entries = []
                    for i, (data, factory) in \
                            list(enumerate(users))[lo:lo + n]:
                        p = os.path.join(root, f"fleet{n}_{rep}_{i}")
                        os.makedirs(p)
                        entries.append(FleetUser(data.user_id, factory(),
                                                 data, p, seed=cfg.seed))
                    recs.extend(sched.run(entries))
                wall = time.perf_counter() - t0
                parity = all(
                    r["error"] is None
                    and r["result"]["trajectory"] == s["trajectory"]
                    for r, s in zip(recs, seq_results))
                s = report.summary(cohort=n, wall_s=wall)
                s["parity_with_sequential"] = parity
                prev = sweep.get(n)
                if prev is not None and not prev["parity_with_sequential"]:
                    continue  # a parity failure poisons the cohort's entry
                if not parity or prev is None \
                        or s["users_per_sec"] > prev["users_per_sec"]:
                    sweep[n] = s

        seq_ups = n_users / seq_s
        _log(f"[sequential] {n_users} users in {seq_s:.1f}s best of "
             f"{reps} ({seq_ups:.3f} users/s)")
        for n in sweep_ns:
            best = sweep[n]
            best["speedup_vs_sequential"] = round(
                best["users_per_sec"] / seq_ups, 2)
            _log(f"[fleet n={n}] best of {reps}: {best['wall_s']:.1f}s "
                 f"({best['users_per_sec']:.3f} users/s, "
                 f"{best['speedup_vs_sequential']}x sequential, occupancy "
                 f"{best['occupancy']}, "
                 f"parity={best['parity_with_sequential']})")
    finally:
        shutil.rmtree(root, ignore_errors=True)

    best_n = max(sweep, key=lambda n: sweep[n]["users_per_sec"] or 0)
    best = sweep[best_n]
    print(json.dumps({
        "metric": f"fleet_users_per_sec_{n_users}u",
        "value": best["users_per_sec"],
        "unit": "users/s",
        "vs_baseline": best["speedup_vs_sequential"],
        "best_cohort": best_n,
        "sequential_users_per_sec": round(seq_ups, 4),
        "parity_with_sequential": all(s["parity_with_sequential"]
                                      for s in sweep.values()),
        "sweep": {str(n): {k: s[k] for k in
                           ("users_per_sec", "occupancy",
                            "speedup_vs_sequential", "wall_s",
                            "mean_device_batch")}
                  for n, s in sweep.items()},
        **_provenance(),
    }))
    return 0


def _skewed_fleet_workload(n_users: int, small: int, n_feat: int,
                           seed: int, *, large_every: int = 4,
                           skew_factor: int = 4):
    """Tail-heavy multi-user workload: most users carry ``small``-song
    pools, every ``large_every``-th carries ``skew_factor * small`` —
    the size distribution where cohort-max padding wastes the most (every
    small user scores the large user's padded rows all run long).
    Returns ``([(UserData, committee_factory), ...], sizes)``."""
    sizes = [small * (skew_factor if (u % large_every == large_every - 1)
                      else 1) for u in range(n_users)]
    return _sized_fleet_workload(sizes, n_feat, seed), sizes


def run_serve_suite(args_ns) -> int:
    """Serve layer vs fleet cohorts vs sequential, on a SKEWED workload.

    The fleet's fixed cohorts pay (a) the cohort-max pool pad on every
    user and (b) the occupancy drain at each cohort's tail; the serve
    layer (``serve.FleetServer``) pads per power-of-two-ish bucket and
    refills slots the moment a session finishes.  This suite races the
    three drivers over IDENTICAL tail-heavy users (every 4th pool is 4×
    the rest) with interleaved best-of-reps timing (2-vCPU drift
    protocol), asserts per-user trajectory parity against the sequential
    loop on EVERY rep, and reports users/sec + per-bucket occupancy +
    admission telemetry.
    """
    import os
    import shutil
    import tempfile

    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.config import ALConfig
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, \
        FleetUser
    from consensus_entropy_tpu.serve import FleetServer, ServeConfig
    from consensus_entropy_tpu.utils import round_up

    cfg = ALConfig(queries=args_ns.k, epochs=args_ns.al_epochs, mode="mc",
                   seed=1987, ckpt_dtype="float32")
    n_users = args_ns.users
    small = args_ns.pool or 120
    users, sizes = _skewed_fleet_workload(n_users, small, 96, cfg.seed)
    # operator-tuned bucket edges: one per distinct pool size class (the
    # realistic deployment; power-of-two is the untuned default)
    widths = tuple(sorted({round_up(s, 8) for s in sizes}))
    _log(f"serve workload: {n_users} users, pool sizes {sizes} "
         f"(bucket edges {list(widths)}), 3 host members, q={cfg.queries}, "
         f"{cfg.epochs} AL iterations")

    root = tempfile.mkdtemp(prefix="serve_bench_")
    reps = args_ns.reps
    sweep_ns = sorted(set(args_ns.fleet))
    try:
        loop = ALLoop(cfg)
        seq_results = None
        seq_s = float("inf")
        fleet_sweep: dict = {}
        serve_sweep: dict = {}
        for rep in range(reps):
            # interleaved: sequential, then fleet-N and serve-N per N —
            # the throttled box slows under sustained load, so ordering
            # a full sweep per side would bias whichever ran first
            t0 = time.perf_counter()
            results = []
            for i, (data, factory) in enumerate(users):
                p = os.path.join(root, f"seq{rep}_{i}")
                os.makedirs(p)
                results.append(loop.run_user(factory(), data, p,
                                             seed=cfg.seed))
            seq_s = min(seq_s, time.perf_counter() - t0)
            if seq_results is None:
                seq_results = results
            elif [r["trajectory"] for r in results] \
                    != [r["trajectory"] for r in seq_results]:
                raise AssertionError("sequential reps diverged")
            traj_of = {r["user"]: r["trajectory"] for r in seq_results}

            def check_parity(recs):
                return all(
                    r["error"] is None
                    and r["result"]["trajectory"] == traj_of[r["user"]]
                    for r in recs) and len(recs) == n_users

            def keep_best(sweep, n, s):
                prev = sweep.get(n)
                if prev is not None and not prev["parity_with_sequential"]:
                    return
                if not s["parity_with_sequential"] or prev is None \
                        or s["users_per_sec"] > prev["users_per_sec"]:
                    sweep[n] = s

            for n in sweep_ns:
                # fleet: fixed cohorts of n, cohort-max padding
                report = FleetReport()
                sched = FleetScheduler(cfg, report=report,
                                       host_workers=args_ns.host_workers,
                                       user_timings=False)
                t0 = time.perf_counter()
                recs = []
                for lo in range(0, n_users, n):
                    entries = [
                        FleetUser(data.user_id, factory(), data,
                                  _mkdir(root, f"fleet{n}_{rep}_{i}"),
                                  seed=cfg.seed)
                        for i, (data, factory) in
                        list(enumerate(users))[lo:lo + n]]
                    recs.extend(sched.run(entries))
                wall = time.perf_counter() - t0
                s = report.summary(cohort=n, wall_s=wall)
                s["parity_with_sequential"] = check_parity(recs)
                keep_best(fleet_sweep, n, s)

                # serve: continuous admission at target occupancy n,
                # bucketed padding
                report = FleetReport()
                sched = FleetScheduler(cfg, report=report,
                                       host_workers=args_ns.host_workers,
                                       user_timings=False,
                                       scoring_by_width=True)
                server = FleetServer(sched, ServeConfig(
                    target_live=n, max_queue=max(n_users, 1),
                    bucket_widths=widths))
                entries = [
                    FleetUser(data.user_id, factory(), data,
                              _mkdir(root, f"serve{n}_{rep}_{i}"),
                              seed=cfg.seed)
                    for i, (data, factory) in enumerate(users)]
                t0 = time.perf_counter()
                recs = server.serve(iter(entries))
                wall = time.perf_counter() - t0
                s = report.summary(cohort=n, wall_s=wall)
                s["parity_with_sequential"] = check_parity(recs)
                keep_best(serve_sweep, n, s)

        seq_ups = n_users / seq_s
        _log(f"[sequential] {n_users} users in {seq_s:.1f}s best of "
             f"{reps} ({seq_ups:.3f} users/s)")
        for n in sweep_ns:
            f, s = fleet_sweep[n], serve_sweep[n]
            for name, best in (("fleet", f), ("serve", s)):
                best["speedup_vs_sequential"] = round(
                    best["users_per_sec"] / seq_ups, 2)
            _log(f"[n={n}] fleet {f['users_per_sec']:.3f} u/s (occ "
                 f"{f['occupancy']}, parity={f['parity_with_sequential']})"
                 f" | serve {s['users_per_sec']:.3f} u/s (occ "
                 f"{s['occupancy']}, per-bucket "
                 f"{s.get('per_bucket')}, "
                 f"parity={s['parity_with_sequential']}) -> serve/fleet "
                 f"{s['users_per_sec'] / f['users_per_sec']:.2f}x")
    finally:
        shutil.rmtree(root, ignore_errors=True)

    best_n = max(serve_sweep,
                 key=lambda n: serve_sweep[n]["users_per_sec"] or 0)
    best = serve_sweep[best_n]
    best_fleet = max(fleet_sweep.values(),
                     key=lambda s: s["users_per_sec"] or 0)
    print(json.dumps({
        "metric": f"serve_users_per_sec_{n_users}u_skewed",
        "value": best["users_per_sec"],
        "unit": "users/s",
        # the acceptance ratio: serve vs the best fleet cohort config on
        # the same skewed workload (>= 1.0 means continuous admission +
        # bucketing beat fixed cohorts + cohort-max padding)
        "vs_baseline": round(best["users_per_sec"]
                             / best_fleet["users_per_sec"], 2),
        "target_live": best_n,
        "vs_sequential": best["speedup_vs_sequential"],
        "sequential_users_per_sec": round(seq_ups, 4),
        "fleet_users_per_sec": best_fleet["users_per_sec"],
        "fleet_vs_sequential": best_fleet["speedup_vs_sequential"],
        "pool_sizes": sizes,
        "bucket_widths": list(widths),
        "per_bucket": best.get("per_bucket"),
        "occupancy": best.get("occupancy"),
        "admission_wait_s": best.get("admission_wait_s"),
        "queue_depth": best.get("queue_depth"),
        "parity_with_sequential": all(
            s["parity_with_sequential"]
            for s in list(serve_sweep.values()) + list(fleet_sweep.values())),
        "sweep": {str(n): {
            "serve_users_per_sec": serve_sweep[n]["users_per_sec"],
            "fleet_users_per_sec": fleet_sweep[n]["users_per_sec"],
            "serve_occupancy": serve_sweep[n]["occupancy"],
            "fleet_occupancy": fleet_sweep[n]["occupancy"],
            "serve_per_bucket": serve_sweep[n].get("per_bucket"),
        } for n in sweep_ns},
        **_provenance(),
    }))
    return 0


def run_slo_suite(args_ns) -> int:
    """SLO planner vs fixed-window admission on the tail-heavy serve
    workload (ISSUE 11).

    Both arms drive the SAME class-aware server (every 3rd user
    ``interactive``, the rest ``batch``, all submitted up front so the
    priority queue actually orders admissions) over IDENTICAL tail-heavy
    users (every 4th pool 4x).  The FIXED arm (``slo_planner=False``)
    is the PR 3 shape — operator-free pow2 buckets, no admission window,
    eager dispatch.  The PLANNER arm derives bucket edges online from
    the quantile sketch and holds partially-formed dispatches while host
    work is in flight (``serve.planner.dispatch_hold``), inside
    per-class SLO headroom.  Per-user trajectory parity against the
    sequential loop is asserted on EVERY rep of both arms; the headline
    is MEAN BUCKET OCCUPANCY (capacity-independent on this throttled
    box, like the fused suite's h2d bytes) — the acceptance bound is
    planner > fixed — with users/sec and per-class admission→finish p95
    (interactive <= batch under load) reported alongside.
    """
    import shutil
    import tempfile

    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.config import ALConfig
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, \
        FleetUser
    from consensus_entropy_tpu.serve import FleetServer, ServeConfig

    cfg = ALConfig(queries=args_ns.k, epochs=args_ns.al_epochs, mode="mc",
                   seed=1987, ckpt_dtype="float32")
    n_users = args_ns.users
    small = args_ns.pool or 120
    n = sorted(set(args_ns.fleet))[-1]
    users, sizes = _skewed_fleet_workload(n_users, small, 96, cfg.seed)
    cls_of = ["interactive" if i % 3 == 2 else "batch"
              for i in range(n_users)]
    _log(f"slo workload: {n_users} users, pool sizes {sizes}, classes "
         f"{cls_of}, target_live {n}, 3 host members, q={cfg.queries}, "
         f"{cfg.epochs} AL iterations")

    root = tempfile.mkdtemp(prefix="slo_bench_")
    reps = args_ns.reps
    try:
        loop = ALLoop(cfg)
        seq_results = None
        seq_s = float("inf")
        arms: dict[str, list] = {"fixed": [], "planner": []}
        for rep in range(reps):
            # interleaved (sequential, fixed, planner per rep) — the
            # 2-vCPU drift protocol every suite here uses
            t0 = time.perf_counter()
            results = []
            for i, (data, factory) in enumerate(users):
                p = _mkdir(root, f"seq{rep}_{i}")
                results.append(loop.run_user(factory(), data, p,
                                             seed=cfg.seed))
            seq_s = min(seq_s, time.perf_counter() - t0)
            if seq_results is None:
                seq_results = results
            elif [r["trajectory"] for r in results] \
                    != [r["trajectory"] for r in seq_results]:
                raise AssertionError("sequential reps diverged")
            traj_of = {r["user"]: r["trajectory"] for r in seq_results}

            for arm, planner_on in (("fixed", False), ("planner", True)):
                report = FleetReport()
                sched = FleetScheduler(cfg, report=report,
                                       host_workers=args_ns.host_workers,
                                       user_timings=False,
                                       scoring_by_width=True)
                server = FleetServer(sched, ServeConfig(
                    target_live=n, max_queue=max(n_users, 1),
                    slo_planner=planner_on, planner_epoch=4))
                entries = [
                    FleetUser(data.user_id, factory(), data,
                              _mkdir(root, f"{arm}{rep}_{i}"),
                              seed=cfg.seed, priority=cls_of[i])
                    for i, (data, factory) in enumerate(users)]
                t0 = time.perf_counter()
                for e in entries:
                    server.submit(e)
                server.close_intake()
                recs = server.serve(())
                wall = time.perf_counter() - t0
                s = report.summary(cohort=n, wall_s=wall)
                s["parity_with_sequential"] = (
                    len(recs) == n_users
                    and all(r["error"] is None
                            and r["result"]["trajectory"]
                            == traj_of[r["user"]] for r in recs))
                arms[arm].append(s)
                _log(f"[rep {rep} {arm}] occupancy={s['occupancy']} "
                     f"users/s={s['users_per_sec']} "
                     f"parity={s['parity_with_sequential']}")

        def mean_occ(arm):
            occ = [s["occupancy"] for s in arms[arm]
                   if s["occupancy"] is not None]
            return round(sum(occ) / len(occ), 3) if occ else None

        def best(arm):
            return max(arms[arm], key=lambda s: s["users_per_sec"] or 0)

        def class_p95(s):
            per = s.get("per_class") or {}
            return {cls: (c.get("admission_to_finish_s") or {}).get("p95")
                    for cls, c in sorted(per.items())}

        seq_ups = n_users / seq_s
        occ_fixed, occ_planner = mean_occ("fixed"), mean_occ("planner")
        bf, bp = best("fixed"), best("planner")
        parity = all(s["parity_with_sequential"]
                     for ss in arms.values() for s in ss)
        if not parity:
            # the acceptance PRECONDITION: a planner that changes
            # per-user results must never produce a green-looking
            # occupancy artifact
            raise AssertionError(
                "slo suite lost per-user parity with the sequential "
                "loop: " + json.dumps({
                    arm: [s["parity_with_sequential"] for s in ss]
                    for arm, ss in arms.items()}))
        _log(f"[sequential] {seq_ups:.3f} users/s best of {reps}")
        _log(f"[fixed]   occupancy {occ_fixed} (mean of {reps}), "
             f"{bf['users_per_sec']:.3f} users/s best, per-class p95 "
             f"{class_p95(bf)}")
        _log(f"[planner] occupancy {occ_planner} (mean of {reps}), "
             f"{bp['users_per_sec']:.3f} users/s best, per-class p95 "
             f"{class_p95(bp)}, edges {bp.get('planner', {}).get('edges')}")

        def arm_line(s, occ):
            p95 = class_p95(s)
            out = {
                "occupancy": occ,
                "users_per_sec": s["users_per_sec"],
                "vs_sequential": round(s["users_per_sec"] / seq_ups, 2),
                "mean_device_batch": s.get("mean_device_batch"),
                "per_bucket": s.get("per_bucket"),
                "per_class_p95_s": p95,
                "interactive_p95_le_batch_p95": (
                    p95.get("interactive") is not None
                    and p95.get("batch") is not None
                    and p95["interactive"] <= p95["batch"]),
                "admission_to_finish_s": s.get("admission_to_finish_s"),
            }
            if s.get("planner") is not None:
                out["planner"] = s["planner"]
            return out

        print(json.dumps({
            "metric": f"slo_planner_mean_occupancy_{n_users}u",
            "value": occ_planner,
            "unit": "occupancy",
            # the acceptance ratio: planner-formed dispatches vs the
            # fixed-window arm's, same users, parity exact on every rep
            "vs_baseline": (round(occ_planner / occ_fixed, 2)
                            if occ_planner and occ_fixed else None),
            "target_live": n,
            "pool_sizes": sizes,
            "classes": cls_of,
            "sequential_users_per_sec": round(seq_ups, 4),
            "fixed": arm_line(bf, occ_fixed),
            "planner": arm_line(bp, occ_planner),
            "per_rep_occupancy": {
                arm: [s["occupancy"] for s in ss]
                for arm, ss in arms.items()},
            "parity_with_sequential": parity,
            **_provenance(),
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return 0


def run_serve_fused_suite(args_ns) -> int:
    """Fused vs unfused serve step on one bucketed workload (ISSUE 8).

    Races two serve arms over IDENTICAL users and seeds — the fused step
    (device-resident ``DevicePoolState``, donated stacks, in-graph
    select→reveal→mask; the default) against ``--no-fuse-step`` (score,
    pull, host bookkeeping, re-upload; the breaker/fallback arm) — with
    per-user trajectory parity against an unfused SEQUENTIAL baseline
    asserted on every rep of both arms.  Timing follows the 2-vCPU drift
    protocol (interleaved reps, best-of per arm), but the headline
    numbers are the capacity-INDEPENDENT transfer metrics this box can
    pin: host→device bytes per select, transfer ops per select, and
    device calls per select — users/sec rides along for context.

    The pool size defaults to 280 songs so the default power-of-two
    bucket pads users to 512: the regime where the unfused arm re-ships
    a 512-wide probs table + masks every iteration while the fused arm
    uploads only the ≤512-wide live block (256 once the pool shrinks
    under the staging bucket) plus a one-time mask upload at admission
    (charged to the counters too — the accounting is symmetric).
    """
    import shutil
    import tempfile

    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.config import ALConfig
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, \
        FleetUser
    from consensus_entropy_tpu.serve import FleetServer, ServeConfig

    cfg = ALConfig(queries=args_ns.k, epochs=args_ns.al_epochs, mode="mc",
                   seed=1987, ckpt_dtype="float32")
    n_users = args_ns.users
    n_songs = args_ns.pool or 280
    target = max(args_ns.fleet)
    users = _fleet_workload(n_users, n_songs, 96, cfg.seed)
    _log(f"serve-fused workload: {n_users} users x {n_songs} songs "
         f"(power-of-two buckets), 3 host members, q={cfg.queries}, "
         f"{cfg.epochs} AL iterations, target_live={target}")

    root = tempfile.mkdtemp(prefix="serve_fused_bench_")
    reps = args_ns.reps
    try:
        loop = ALLoop(cfg, fuse_step=False)
        seq_results = None
        seq_s = float("inf")
        arms: dict = {}
        for rep in range(reps):
            t0 = time.perf_counter()
            results = []
            for i, (data, factory) in enumerate(users):
                p = _mkdir(root, f"seq{rep}_{i}")
                results.append(loop.run_user(factory(), data, p,
                                             seed=cfg.seed))
            seq_s = min(seq_s, time.perf_counter() - t0)
            if seq_results is None:
                seq_results = results
            elif [r["trajectory"] for r in results] \
                    != [r["trajectory"] for r in seq_results]:
                raise AssertionError("sequential reps diverged")
            traj_of = {r["user"]: r["trajectory"] for r in seq_results}

            for arm, fuse in (("fused", True), ("unfused", False)):
                report = FleetReport()
                sched = FleetScheduler(cfg, report=report,
                                       host_workers=args_ns.host_workers,
                                       user_timings=False,
                                       scoring_by_width=True,
                                       fuse_step=fuse)
                server = FleetServer(sched, ServeConfig(
                    target_live=target, max_queue=max(n_users, 1)))
                entries = [
                    FleetUser(data.user_id, factory(), data,
                              _mkdir(root, f"{arm}{rep}_{i}"),
                              seed=cfg.seed)
                    for i, (data, factory) in enumerate(users)]
                t0 = time.perf_counter()
                recs = server.serve(iter(entries))
                wall = time.perf_counter() - t0
                parity = len(recs) == n_users and all(
                    r["error"] is None
                    and r["result"]["trajectory"] == traj_of[r["user"]]
                    for r in recs)
                if not parity:
                    raise AssertionError(
                        f"{arm} arm lost parity on rep {rep}")
                s = report.summary(cohort=target, wall_s=wall)
                # uploaded bytes/ops are deterministic per arm (dispatch
                # GROUPING varies with scheduling timing, so the
                # calls-per-select figure may wiggle) — assert the
                # deterministic part instead of averaging; keep the
                # best-wall rep's summary
                prev = arms.get(arm)
                if prev is not None and any(
                        prev["transfer"][k] != s["transfer"][k]
                        for k in ("h2d_bytes", "h2d_ops", "selects")):
                    raise AssertionError(
                        f"{arm} transfer bytes drifted across reps: "
                        f"{prev['transfer']} vs {s['transfer']}")
                if prev is None or s["users_per_sec"] > \
                        prev["users_per_sec"]:
                    arms[arm] = s

        seq_ups = n_users / seq_s
        f, u = arms["fused"], arms["unfused"]
        tf, tu = f["transfer"], u["transfer"]
        assert tf["h2d_bytes"] < tu["h2d_bytes"], \
            "fused arm did not reduce host->device bytes"
        assert tf["device_calls_per_select"] \
            < u["transfer"]["device_calls_per_select"], \
            "fused arm did not reduce device calls per iteration"
        for arm, s in arms.items():
            s["speedup_vs_sequential"] = round(
                s["users_per_sec"] / seq_ups, 2)
            _log(f"[serve {arm}] best of {reps}: {s['wall_s']:.1f}s "
                 f"({s['users_per_sec']:.3f} users/s, occupancy "
                 f"{s['occupancy']}) transfer={s['transfer']}")
        _log(f"[reduction] h2d bytes/select {tu['h2d_bytes_per_select']}"
             f" -> {tf['h2d_bytes_per_select']} "
             f"({tu['h2d_bytes_per_select'] / max(tf['h2d_bytes_per_select'], 1):.2f}x), "
             f"device calls/select {tu['device_calls_per_select']} -> "
             f"{tf['device_calls_per_select']}")
    finally:
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps({
        "metric": f"serve_fused_step_{n_users}u",
        "value": f["users_per_sec"],
        "unit": "users/s",
        # users/sec ratio rides along for context; the acceptance
        # metrics are the transfer reductions below (capacity-independent
        # on the throttled box, where users/sec drifts ~2x)
        "vs_baseline": round(f["users_per_sec"] / u["users_per_sec"], 2),
        "target_live": target,
        "sequential_users_per_sec": round(seq_ups, 4),
        "unfused_users_per_sec": u["users_per_sec"],
        "parity_with_sequential": True,  # asserted on every rep
        "pool_songs": n_songs,
        "transfer_fused": tf,
        "transfer_unfused": tu,
        "h2d_bytes_per_select_reduction": round(
            tu["h2d_bytes_per_select"]
            / max(tf["h2d_bytes_per_select"], 1), 2),
        "device_calls_per_select_reduction": round(
            tu["device_calls_per_select"]
            / tf["device_calls_per_select"], 2),
        "occupancy_fused": f.get("occupancy"),
        "occupancy_unfused": u.get("occupancy"),
        **_provenance(),
    }))
    return 0


def run_obs_suite(args_ns) -> int:
    """Introspection overhead: plane-ON vs plane-OFF serve runs
    (ISSUE 9's tracing arms, grown to ISSUE 15's full plane).

    Two serve runs over IDENTICAL users and seeds — one with the whole
    introspection plane live (span tracer writing a real
    ``spans.jsonl``, compile events, status snapshots refreshing, alert
    watcher evaluating), one with everything off (the
    ``--no-introspection --no-trace`` arm) — interleaved with
    alternating order per rep (throttled-box discipline), per-user
    trajectory parity asserted against a sequential baseline on EVERY
    rep of BOTH arms.

    The acceptance number (overhead <= 3%) is the MEDIAN of per-rep
    paired traced/bare wall ratios (adjacent runs, warmed, order
    alternating) — pairing cancels this box's slow load drift, and the
    identical-arm noise floor is measured IN-SUITE the same way and
    included in the artifact so the headline reads in context.  A
    deterministic companion pin rides along: the per-span emit cost
    (tight-loop microbench against the same filesystem) times the run's
    span count, as a share of traced wall — the capacity-independent
    "work added" figure in the PR 7/8 byte/call tradition.  Each traced
    rep's artifacts are validated too: metrics lines against the
    schema-v2 event table, spans merged orphan-free, Chrome export
    loadable.
    """
    import os
    import shutil
    import tempfile

    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.config import ALConfig
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, \
        FleetUser
    from consensus_entropy_tpu.obs import export
    from consensus_entropy_tpu.obs.trace import Tracer
    from consensus_entropy_tpu.serve import FleetServer, ServeConfig

    cfg = ALConfig(queries=args_ns.k, epochs=args_ns.al_epochs, mode="mc",
                   seed=1987, ckpt_dtype="float32")
    n_users = args_ns.users
    users = _fleet_workload(n_users, args_ns.pool or 120, 96, cfg.seed)
    target = min(max(args_ns.fleet), n_users)
    _log(f"obs workload: {n_users} users x {args_ns.pool or 120} songs, "
         f"3 host members, q={cfg.queries}, {cfg.epochs} AL iterations, "
         f"target_live={target}")

    root = tempfile.mkdtemp(prefix="obs_bench_")
    reps = args_ns.reps
    try:
        loop = ALLoop(cfg)
        # one sequential pass pins the ground-truth trajectories (the
        # runs are deterministic; the timed race is traced vs untraced)
        seq_results = []
        for i, (data, factory) in enumerate(users):
            p = _mkdir(root, f"seq_{i}")
            seq_results.append(loop.run_user(factory(), data, p,
                                             seed=cfg.seed))
        traj_of = {r["user"]: r["trajectory"] for r in seq_results}

        def serve_once(tag, rep, tracer, metrics_path=None,
                       status_dir=None):
            report = FleetReport(metrics_path)
            sched = FleetScheduler(cfg, report=report,
                                   host_workers=args_ns.host_workers,
                                   user_timings=False,
                                   scoring_by_width=True, tracer=tracer,
                                   compile_events=status_dir is not None)
            status = alerts = None
            if status_dir is not None:
                # the plane-ON arm pays the WHOLE introspection plane:
                # snapshots refreshing at the production cadence and the
                # alert watcher evaluating per write
                from consensus_entropy_tpu.obs.alerts import AlertWatcher
                from consensus_entropy_tpu.obs.status import StatusWriter

                status = StatusWriter(status_dir, "local",
                                      interval_s=0.2)
                alerts = AlertWatcher(report)
            server = FleetServer(sched, ServeConfig(
                target_live=target, max_queue=max(n_users, 1)),
                status=status, alerts=alerts)
            entries = [
                FleetUser(data.user_id, factory(), data,
                          _mkdir(root, f"{tag}_{rep}_{i}"), seed=cfg.seed)
                for i, (data, factory) in enumerate(users)]
            t0 = time.perf_counter()
            recs = server.serve(iter(entries))
            wall = time.perf_counter() - t0
            assert len(recs) == n_users and all(
                r["error"] is None
                and r["result"]["trajectory"] == traj_of[r["user"]]
                for r in recs), f"{tag} rep {rep}: parity failure"
            return wall, report

        # untimed warm-up: the first serve run pays the per-width jit
        # compiles, which must not land inside either arm's rep 0
        serve_once("warm", 0, None)
        best = {"traced": float("inf"), "bare": float("inf")}
        ratios = []  # per-rep traced/bare wall (adjacent runs)
        span_stats = None
        for rep in range(reps):
            # interleave, alternating which arm goes first so the box's
            # load drift can't systematically favor one side
            walls = {}
            order = ["traced", "bare"] if rep % 2 == 0 else ["bare",
                                                             "traced"]
            for arm in order:
                if arm != "traced":
                    walls["bare"], _ = serve_once("bare", rep, None)
                    best["bare"] = min(best["bare"], walls["bare"])
                    continue
                spans_path = os.path.join(root, f"spans_{rep}.jsonl")
                metrics_path = os.path.join(
                    root, f"metrics_{rep}", "fleet_metrics.jsonl")
                status_dir = os.path.join(root, f"status_{rep}")
                tracer = Tracer(spans_path,
                                run_id=f"{cfg.mode}-{cfg.seed}")
                walls["traced"], report = serve_once(
                    "traced", rep, tracer, metrics_path,
                    status_dir=status_dir)
                tracer.close()
                report.write_summary(cohort=target)
                report.close()
                # artifact gates, every traced rep: schema-valid metrics,
                # orphan-free merged spans, loadable Chrome export, and
                # a schema-valid final status snapshot
                errs = export.validate_metrics_file(metrics_path)
                assert errs == [], f"schema violations: {errs[:3]}"
                from consensus_entropy_tpu.obs.status import (
                    read_status,
                    status_path,
                    validate_status,
                )

                snap = read_status(status_path(status_dir, "local"))
                assert snap is not None and validate_status(snap) == []
                spans = export.load_spans([spans_path])
                assert spans and export.orphan_spans(spans) == []
                json.dumps(export.chrome_trace(spans))
                span_stats = {"n_spans": len(spans),
                              "bytes": os.path.getsize(spans_path)}
                best["traced"] = min(best["traced"], walls["traced"])
            ratios.append(walls["traced"] / walls["bare"])
            _log(f"[rep {rep}] traced {walls['traced']:.2f}s / bare "
                 f"{walls['bare']:.2f}s = {ratios[-1]:.3f}")
        # the box's own noise floor, measured the same way the overhead
        # is: identical bare arms, paired, |ratio - 1|
        noise = []
        for rep in range(2):
            w1, _ = serve_once("na", rep, None)
            w2, _ = serve_once("nb", rep, None)
            noise.append(abs(w1 / w2 - 1.0))
        # deterministic per-span emit cost against the same filesystem
        # (tight loop, single thread): the "work added" companion pin
        mb = Tracer(os.path.join(root, "mb.jsonl"), run_id="mb")
        t0 = time.perf_counter()
        for i in range(1000):
            mb.end(mb.begin("al_iter", parent=mb.user_ctx("u"),
                            key=("u", i), user="u", epoch=i))
        per_span_s = (time.perf_counter() - t0) / 1000.0
        mb.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    import statistics

    traced_ups = n_users / best["traced"]
    bare_ups = n_users / best["bare"]
    wall_median_pct = round((statistics.median(ratios) - 1.0) * 100.0, 2)
    noise_pct = round(100.0 * max(noise), 2)
    emit_cost_pct = round(100.0 * span_stats["n_spans"] * per_span_s
                          / best["traced"], 3)
    _log(f"wall A/B median {wall_median_pct:+.2f}% (the <=3% pin) at a "
         f"measured identical-arm noise floor of ±{noise_pct}%; "
         f"deterministic span-emit cost {emit_cost_pct}% "
         f"({span_stats['n_spans']} spans x {per_span_s * 1e6:.0f}us / "
         f"{best['traced']:.2f}s); traced {traced_ups:.3f} vs bare "
         f"{bare_ups:.3f} users/s best-of-{reps}")
    print(json.dumps({
        "metric": f"obs_introspection_overhead_{n_users}u",
        # the acceptance number (<= 3): median of per-rep paired
        # traced/bare wall ratios — pairing cancels the box's slow
        # drift; the identical-arm noise floor below gives the error bar
        "value": wall_median_pct,
        "unit": "%",
        "vs_baseline": round(traced_ups / bare_ups, 4),
        "wall_noise_floor_pct": noise_pct,
        # capacity-independent companion: spans/run x measured us/span
        # over traced wall (the work the tracer actually adds)
        "span_emit_cost_pct": emit_cost_pct,
        "span_emit_us": round(per_span_s * 1e6, 1),
        "traced_users_per_sec": round(traced_ups, 4),
        "untraced_users_per_sec": round(bare_ups, 4),
        "parity_every_rep": True,  # asserted above, every rep, both arms
        "spans_per_run": span_stats["n_spans"],
        "spans_bytes_per_run": span_stats["bytes"],
        "schema_valid_every_rep": True,
        "reps": reps,
        **_provenance(),
    }))
    return 0


def run_serve_faults_suite(args_ns) -> int:
    """Crash-safe serving under a FLAKY user mix: recovered-users/sec.

    Every ``flaky_every``-th user carries a uniquely-named victim member
    whose retrain raises on its first two hits (per-member fault
    counting), so that user burns its initial session AND its in-engine
    resume, then recovers through serve-layer backoff re-admission; a
    straggler ``pool.score`` delay trips the session watchdog once, and a
    transient stacked-dispatch fault opens the per-bucket circuit breaker
    (per-user fallback, half-open recovery).  Sequential UNFAULTED runs
    are the ground truth: the suite asserts every user still finishes
    with bit-identical trajectories, then reports the faulted serve
    side's users/sec (the price of recovery) with watchdog/breaker/
    requeue counts.  Reps are interleaved best-of (2-vCPU drift
    protocol); the injector is re-installed per rep so hit counts are
    rep-local.
    """
    import os
    import shutil
    import tempfile

    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.config import ALConfig
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, \
        FleetUser
    from consensus_entropy_tpu.resilience import faults
    from consensus_entropy_tpu.resilience.faults import FaultRule
    from consensus_entropy_tpu.serve import FleetServer, ServeConfig
    from consensus_entropy_tpu.utils import round_up

    # min_members=3: ANY quarantined member exhausts the 3-member
    # committee, so a flaky user's faulted session terminates (instead of
    # being silently absorbed) and the serve-layer recovery ladder —
    # evict -> resume -> backoff re-admission — actually runs
    cfg = ALConfig(queries=args_ns.k, epochs=args_ns.al_epochs, mode="mc",
                   seed=1987, ckpt_dtype="float32", min_members=3)
    n_users = args_ns.users
    small = args_ns.pool or 120
    flaky_every = 3
    sizes = [small * (4 if (u % 4 == 3) else 1) for u in range(n_users)]
    flaky = [u % flaky_every == flaky_every - 1 for u in range(n_users)]
    sgd1_names = [f"sgd.flaky{u}" if flaky[u] else "sgd.it_1"
                  for u in range(n_users)]
    users = _sized_fleet_workload(sizes, 96, cfg.seed,
                                  sgd1_names=sgd1_names)
    widths = tuple(sorted({round_up(s, 8) for s in sizes}))
    n = args_ns.fleet[0] if args_ns.fleet else 4

    def rules():
        return ([FaultRule("member.retrain", "raise", at=1, times=2,
                           member=f"sgd.flaky{u}")
                 for u in range(n_users) if flaky[u]]
                + [FaultRule("pool.score", "delay", at=5, delay_s=1.2),
                   FaultRule("serve.dispatch", "transient", at=3)])

    _log(f"serve-faults workload: {n_users} users (flaky every "
         f"{flaky_every}th: {sum(flaky)}), pool sizes {sizes}, bucket "
         f"edges {list(widths)}, target_live={n}, q={cfg.queries}, "
         f"{cfg.epochs} AL iterations")

    root = tempfile.mkdtemp(prefix="serve_faults_bench_")
    reps = args_ns.reps
    try:
        loop = ALLoop(cfg)
        seq_results = None
        seq_s = float("inf")
        best = None
        for rep in range(reps):
            # interleaved: unfaulted sequential ground truth, then the
            # fault-injected serve run, per rep (2-vCPU drift protocol)
            t0 = time.perf_counter()
            results = []
            for i, (data, factory) in enumerate(users):
                p = _mkdir(root, f"seq{rep}_{i}")
                results.append(loop.run_user(factory(), data, p,
                                             seed=cfg.seed))
            seq_s = min(seq_s, time.perf_counter() - t0)
            if seq_results is None:
                seq_results = results
            traj_of = {r["user"]: r["trajectory"] for r in seq_results}

            from consensus_entropy_tpu.al import workspace as _ws

            entries = [
                FleetUser(data.user_id, factory(), data,
                          (p := _mkdir(root, f"serve{rep}_{i}")),
                          seed=cfg.seed,
                          # resume-after-eviction reloads the committee
                          # from the workspace's durable checkpoints (the
                          # members' mid-run partial_fit state — a
                          # pristine rebuild would diverge)
                          committee_factory=lambda p=p:
                          _ws.load_committee(p))
                for i, (data, factory) in enumerate(users)]
            report = FleetReport()
            with faults.inject(*rules()) as inj:
                sched = FleetScheduler(
                    cfg, report=report, host_workers=args_ns.host_workers,
                    user_timings=False, scoring_by_width=True,
                    # a small batch window phase-aligns same-bucket
                    # sessions so the dispatch fault lands on a STACKED
                    # call — the breaker's trigger — instead of a
                    # singleton
                    batch_window_s=0.05)
                server = FleetServer(sched, ServeConfig(
                    target_live=n, max_queue=max(n_users, 1),
                    bucket_widths=widths, watchdog_s=0.6,
                    failure_budget=3, backoff_base_s=0.02,
                    backoff_max_s=0.2, breaker_threshold=1,
                    breaker_cooldown_s=0.5))
                t0 = time.perf_counter()
                recs = server.serve(iter(entries))
                wall = time.perf_counter() - t0
            s = report.summary(cohort=n, wall_s=wall)
            s["parity_with_sequential"] = (
                len(recs) == n_users and all(
                    r["error"] is None
                    and r["result"]["trajectory"] == traj_of[r["user"]]
                    for r in recs))
            s["faults_fired"] = len(inj.fired)
            _log(f"[rep {rep}] serve+faults {s['users_done']}/{n_users} "
                 f"users in {wall:.1f}s ({s['users_per_sec']:.3f} u/s, "
                 f"parity={s['parity_with_sequential']}, "
                 f"fired={s['faults_fired']}, "
                 f"evictions={s['evictions']}, resumes={s['resumes']}, "
                 f"requeues={s.get('requeues', 0)}, "
                 f"watchdog={s.get('watchdog_evictions', 0)}, "
                 f"breaker={s.get('breaker_trips', 0)})")
            if not s["parity_with_sequential"]:
                raise AssertionError(
                    f"faulted serve rep {rep} lost parity: "
                    + repr([r["user"] for r in recs
                            if r["error"] is not None]))
            if best is None or s["users_per_sec"] > best["users_per_sec"]:
                best = s
    finally:
        shutil.rmtree(root, ignore_errors=True)

    seq_ups = n_users / seq_s
    print(json.dumps({
        "metric": f"serve_faults_recovered_users_per_sec_{n_users}u",
        "value": best["users_per_sec"],
        "unit": "users/s",
        # the acceptance ratio: faulted-serve throughput vs UNFAULTED
        # sequential — how much of the raw throughput survives a flaky
        # user mix plus watchdog/breaker drills, with zero lost users
        "vs_baseline": round(best["users_per_sec"] / seq_ups, 2),
        "target_live": n,
        "sequential_unfaulted_users_per_sec": round(seq_ups, 4),
        "users_done": best["users_done"],
        "users_failed": best["users_failed"],
        "flaky_users": sum(flaky),
        "faults_fired": best["faults_fired"],
        "evictions": best["evictions"],
        "resumes": best["resumes"],
        "requeues": best.get("requeues", 0),
        "watchdog_evictions": best.get("watchdog_evictions", 0),
        "breaker_trips": best.get("breaker_trips", 0),
        "dispatch_failures": best.get("dispatch_failures", 0),
        "users_poisoned": best.get("users_poisoned", 0),
        "occupancy": best.get("occupancy"),
        "per_bucket": best.get("per_bucket"),
        "parity_with_sequential": True,
        **_provenance(),
    }))
    return 0


def run_qbdc_suite(args_ns) -> int:
    """QBDC (query-by-dropout-committee) vs the stored-committee mc path.

    The paper's committee is ``--members`` (default 20) STORED CNN models
    per user; qbdc is ONE CNN forwarded under K seeded dropout masks
    (``Committee.qbdc_pool_probs``), so committee width is a vmap width
    and per-user device memory is one weight set regardless of K.  This
    suite measures, on an identical synthetic waveform workload:

    - **K-sweep scoring throughput** (K in ``--qbdc-sweep``, default
      8/20/64): AL scoring passes/sec of the qbdc chain (crop forward +
      dropout heads + fused consensus->entropy->top-k) vs the 20-model
      stored-committee mc chain — interleaved best-of ``--reps`` windows
      (the throttled-image discipline the fleet suite uses).
    - **per-user device memory**: parameter bytes a user's committee
      pins in device memory — stored = M x member; qbdc = 1 x member at
      EVERY K (the acceptance bound: K=64 below the 20-model footprint).
    - **top-k overlap**: |top-k(qbdc) ∩ top-k(mc)| / k per K on the same
      iteration key — how far the mask committee's ranking agrees with
      the stored ensemble it replaces (different acquisition functions;
      overlap quantifies, parity is not expected).
    - **users/sec**: 2-user end-to-end AL runs (score -> select ->
      reveal -> retrain -> eval), qbdc@20 vs stored-mc@20, interleaved
      best-of reps.
    """
    import os
    import shutil
    import tempfile

    import jax

    # the CNN crop path requires prefix-stable threefry (this image's
    # 0.4.37 defaults the flag off; tests/CLI set it the same way)
    jax.config.update("jax_threefry_partitionable", True)

    from consensus_entropy_tpu.al.loop import ALLoop, UserData
    from consensus_entropy_tpu.config import ALConfig, CNNConfig, TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.models import short_cnn
    from consensus_entropy_tpu.models.committee import (
        CNNMember,
        Committee,
        FramePool,
    )
    from consensus_entropy_tpu.ops import scoring as ops_scoring

    cnn_cfg = CNNConfig(n_channels=8, n_mels=32, n_layers=5,
                        input_length=8192)
    tc = TrainConfig(batch_size=2)
    stored_m = args_ns.members or 20
    n_songs = args_ns.pool or 48
    k = args_ns.k
    sweep_ks = sorted(set(args_ns.qbdc_sweep))
    reps = args_ns.reps
    seed = 1987

    def make_user(uid, u_seed):
        rng = np.random.default_rng(u_seed)
        centers = rng.standard_normal((4, 16)).astype(np.float32) * 2.5
        rows, sids, labels = [], [], {}
        for i in range(n_songs):
            sid = f"song{i:03d}"
            c = int(rng.integers(0, 4))
            labels[sid] = c
            kk = int(rng.integers(3, 7))
            rows.append(centers[c]
                        + rng.standard_normal((kk, 16)).astype(np.float32))
            sids += [sid] * kk
        pool = FramePool(np.vstack(rows), sids)
        data = UserData(uid, pool, labels, hc_rows=None)
        wrng = np.random.default_rng(u_seed + 7)
        waves = {s: wrng.standard_normal(9000).astype(np.float32)
                 for s in pool.song_ids}
        data.store = DeviceWaveformStore(waves, cnn_cfg.input_length)
        return data

    def cnn_members(n):
        return [CNNMember(f"cnn{i}", short_cnn.init_variables(
            jax.random.key(seed + i), cnn_cfg), cnn_cfg, tc)
            for i in range(n)]

    def stored_committee():
        return Committee([], cnn_members(stored_m), cnn_cfg, tc)

    def qbdc_committee():
        return Committee([], cnn_members(1), cnn_cfg, tc)

    def param_bytes(committee):
        return int(sum(
            np.asarray(leaf).size * np.asarray(leaf).dtype.itemsize
            for m in committee.cnn_members
            for leaf in jax.tree.leaves(m.variables)))

    data = make_user("u_score", seed)
    songs = data.pool.song_ids
    mask = np.ones(n_songs, bool)
    fns = ops_scoring.make_scoring_fns(k=k)
    stored = stored_committee()
    single = qbdc_committee()
    stored_bytes = param_bytes(stored)
    qbdc_bytes = param_bytes(single)
    _log(f"qbdc workload: {n_songs} songs, stored committee M={stored_m} "
         f"({stored_bytes/1e6:.2f} MB/user), qbdc member "
         f"({qbdc_bytes/1e6:.2f} MB/user), K sweep {sweep_ks}, k={k}")

    def mc_pass(it):
        key = jax.random.fold_in(jax.random.key(seed), it)
        probs = stored.predict_songs_cnn(data.store, songs, key)
        res = fns["mc"](probs, mask)
        jax.block_until_ready(res.entropy)
        return res

    def qbdc_pass(it, kk):
        key = jax.random.fold_in(jax.random.key(seed), it)
        probs = single.qbdc_pool_probs(data.store, songs, key, k=kk)
        res = fns["qbdc"](probs, mask)
        jax.block_until_ready(res.entropy)
        return res

    passes = 3  # per timed window

    def window(fn):
        t0 = time.perf_counter()
        for it in range(passes):
            fn(1 + it)
        return (time.perf_counter() - t0) / passes

    # warm-up compiles (untimed), then interleaved best-of-reps windows
    mc_res0 = mc_pass(0)
    q_res0 = {kk: qbdc_pass(0, kk) for kk in sweep_ks}
    best_mc = float("inf")
    best_q = {kk: float("inf") for kk in sweep_ks}
    for _ in range(reps):
        best_mc = min(best_mc, window(mc_pass))
        for kk in sweep_ks:
            best_q[kk] = min(best_q[kk],
                             window(lambda it, kk=kk: qbdc_pass(it, kk)))
    _log(f"[stored mc M={stored_m}] {best_mc*1e3:.1f} ms/pass "
         f"({1.0/best_mc:.2f} passes/s)")

    def topk_set(res):
        return set(np.asarray(res.indices).tolist())

    sweep = {}
    for kk in sweep_ks:
        overlap = len(topk_set(q_res0[kk]) & topk_set(mc_res0)) / k
        sweep[kk] = {
            "passes_per_sec": round(1.0 / best_q[kk], 3),
            "ms_per_pass": round(best_q[kk] * 1e3, 2),
            "speedup_vs_stored_mc": round(best_mc / best_q[kk], 2),
            "topk_overlap_vs_stored_mc": round(overlap, 3),
            "device_param_bytes_per_user": qbdc_bytes,
        }
        _log(f"[qbdc K={kk}] {best_q[kk]*1e3:.1f} ms/pass "
             f"({sweep[kk]['passes_per_sec']} passes/s, "
             f"{sweep[kk]['speedup_vs_stored_mc']}x stored, overlap "
             f"{sweep[kk]['topk_overlap_vs_stored_mc']})")

    # -- end-to-end users/sec: 2-user AL runs, interleaved best-of-reps --
    n_users = 2
    al_users = [make_user(f"u{i}", seed + 10 + i) for i in range(n_users)]
    cfg_mc = ALConfig(queries=k, epochs=args_ns.al_epochs, mode="mc",
                      seed=seed, ckpt_dtype="float32")
    cfg_q = ALConfig(queries=k, epochs=args_ns.al_epochs, mode="qbdc",
                     seed=seed, ckpt_dtype="float32", qbdc_k=stored_m)
    root = tempfile.mkdtemp(prefix="qbdc_bench_")
    best_al = {"stored_mc": float("inf"), "qbdc": float("inf")}
    try:
        for rep in range(reps):
            for tag, cfg, com_fn in (
                    ("stored_mc", cfg_mc, stored_committee),
                    ("qbdc", cfg_q, qbdc_committee)):
                loop = ALLoop(cfg, retrain_epochs=1)
                t0 = time.perf_counter()
                for i, u in enumerate(al_users):
                    p = os.path.join(root, f"{tag}_{rep}_{i}")
                    os.makedirs(p)
                    loop.run_user(com_fn(), u, p, seed=cfg.seed)
                best_al[tag] = min(best_al[tag],
                                   time.perf_counter() - t0)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    ups = {tag: n_users / s for tag, s in best_al.items()}
    _log(f"[AL users/sec] stored mc {ups['stored_mc']:.3f}, "
         f"qbdc@{stored_m} {ups['qbdc']:.3f} "
         f"({ups['qbdc']/ups['stored_mc']:.2f}x)")

    k64 = max(sweep_ks)
    print(json.dumps({
        "metric": f"qbdc_users_per_sec_{n_users}u_K{stored_m}",
        "value": round(ups["qbdc"], 4),
        "unit": "users/s",
        "vs_baseline": round(ups["qbdc"] / ups["stored_mc"], 2),
        "stored_mc_users_per_sec": round(ups["stored_mc"], 4),
        "al_epochs": args_ns.al_epochs,
        "queries": k,
        "n_songs": n_songs,
        "stored_members": stored_m,
        "stored_committee_param_bytes_per_user": stored_bytes,
        "qbdc_param_bytes_per_user": qbdc_bytes,
        # the acceptance bound: per-user device memory at the LARGEST K
        # stays below the 20-model stored-committee footprint (qbdc
        # weights don't scale with K; masks are transient activations)
        "memory_at_max_K_below_stored": bool(qbdc_bytes < stored_bytes),
        "max_K": k64,
        "sweep": {str(kk): sweep[kk] for kk in sweep_ks},
        **_provenance(),
    }))
    return 0


def run_cnn_fleet_suite(args_ns) -> int:
    """Cross-user stacked CNN device path: users/sec + mean_device_batch
    of a same-bucket CNN cohort vs the per-user CNN dispatch path.

    Both arms run the SAME fleet engine over the identical synthetic
    waveform workload and seeds — the only difference is
    ``FleetScheduler(stack_cnn=...)``: stacked groups the cohort's CNN
    probs production / qbdc dropout committees / retrain epochs into ONE
    device dispatch per round (``models.committee.run_device_plans``);
    per-user is the pre-stacking shape (CNN work inline, one dispatch per
    user per step).  Parity with the sequential ``ALLoop.run_user``
    trajectories is asserted on EVERY rep for BOTH arms and both modes
    (mc stored committee, qbdc dropout committee), so the reported
    speedup is for bit-identical per-user results.  Timing reps are
    interleaved (each arm once per rep, best-of-reps per arm) — the
    throttled-image discipline of the fleet suite.

    Because per-user rows are bit-identical, the two arms run EQUAL
    device FLOPs (``lax.map`` over users; vmapped convs would lower to
    different, non-bitwise kernels) — the stacked arm's users/sec win is
    host/device OVERLAP plus dispatch amortization, so it is bounded by
    the box's real parallel capacity, measured and recorded as
    ``host_parallel_speedup`` (this throttled 2-vCPU image has been
    observed as low as ~1.1x: two perfectly parallel workers gain 10%).
    ``mean_device_batch`` and the per-fn dispatch counts are the
    capacity-independent structural metrics: one dispatch PER COHORT
    instead of per user, which is what closes the arithmetic-intensity
    gap on a real accelerator (ISSUE 7 / BENCH_cnn_r05 MFU analysis).
    """
    import os
    import shutil
    import tempfile

    import jax

    # the CNN crop path requires prefix-stable threefry (this image's
    # 0.4.37 defaults the flag off; tests/CLI set it the same way)
    jax.config.update("jax_threefry_partitionable", True)

    from consensus_entropy_tpu.al.loop import ALLoop, UserData
    from consensus_entropy_tpu.config import ALConfig, CNNConfig, TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, \
        FleetUser
    from consensus_entropy_tpu.models import short_cnn
    from consensus_entropy_tpu.models.committee import (
        CNNMember,
        Committee,
        FramePool,
    )

    cnn_cfg = CNNConfig(n_channels=4, n_mels=32, n_layers=5,
                        input_length=8192)
    tc = TrainConfig(batch_size=2)
    n_users = args_ns.users
    n_songs = args_ns.pool or 120
    reps = args_ns.reps
    seed = 1987
    qbdc_k = 8
    retrain_epochs = 1
    # hold a dispatch briefly while host futures are outstanding so the
    # cohort phase-aligns into FULL stacked plan groups (stable cohort
    # geometry = one compiled program per plan kind; see the README fleet
    # section on batch_window_s).  Inert for the per-user arm: its CNN
    # sessions run everything inline, so there are never host futures to
    # wait on — the two arms stay comparable.
    batch_window_s = 0.25

    def make_user(uid, u_seed):
        rng = np.random.default_rng(u_seed)
        n_feat = 96
        centers = rng.standard_normal((4, n_feat)).astype(np.float32) * 2.5
        rows, sids, labels = [], [], {}
        for i in range(n_songs):
            sid = f"song{i:03d}"
            c = int(rng.integers(0, 4))
            labels[sid] = c
            # 40-90 frames/song: an AMG-like pool carries tens of frames
            # per song, and the host members' sklearn blocks (pool
            # predict_proba, gated test predicts) scale with it — the
            # host share the stacked arm overlaps under its device
            # dispatches.  A 4-9-frame pool makes host work a rounding
            # error and the A/B measures pure dispatch overhead instead.
            kk = int(rng.integers(40, 90))
            rows.append(centers[c] + rng.standard_normal(
                (kk, n_feat)).astype(np.float32))
            sids += [sid] * kk
        pool = FramePool(np.vstack(rows), sids)
        data = UserData(uid, pool, labels, hc_rows=None)
        wrng = np.random.default_rng(u_seed + 7)
        waves = {s: wrng.standard_normal(9000).astype(np.float32)
                 for s in pool.song_ids}
        data.store = DeviceWaveformStore(waves, cnn_cfg.input_length)
        return data

    def committee_fn(data, u_seed, n_members, hosts):
        # personalized committees: each user's member inits draw from its
        # own seed, so stacked rows can't accidentally pass parity by
        # weight sharing.  mc is the paper's MIXED shape (sklearn hosts +
        # CNN members): the per-step offload split is part of what this
        # suite measures — the baseline arm (stack_cnn=False) runs a CNN
        # session's sklearn blocks inline (the old whole-session gate),
        # the stacked arm rides them on the worker pool overlapping
        # peers' device dispatches.
        cnns = [CNNMember(f"cnn{i}", short_cnn.init_variables(
                    jax.random.key(u_seed + i), cnn_cfg), cnn_cfg, tc)
                for i in range(n_members)]
        host = []
        if hosts:
            from consensus_entropy_tpu.models.sklearn_members import (
                GNBMember,
                SGDMember,
            )

            X = data.pool.X
            y = np.array([data.labels[s] for s in np.repeat(
                data.pool.song_ids, data.pool.counts)], np.int32)
            host = [GNBMember("gnb.it_0").fit(X, y),
                    SGDMember("sgd.it_0", seed=0).fit(X, y),
                    SGDMember("sgd.it_1", seed=1).fit(X, y)]
        return Committee(host, cnns, cnn_cfg, tc)

    def host_parallel_speedup() -> float:
        """Measured parallel capacity of THIS box at bench time: the
        speedup of two GIL-releasing single-threaded numpy workers run on
        two threads vs back-to-back.  The stacked arm's users/sec win is
        overlap (host blocks under the device stream) on equal-FLOP
        bit-identical work, so it is bounded above by this number — on a
        throttled-shares image it has been measured anywhere from ~1.1
        (both vCPUs contending for ~one core of real capacity) to ~2.0.
        Recorded in the artifact so the A/B ratio is read against what
        the hardware offered during the run, the same reason reps are
        interleaved."""
        import threading

        a = np.random.default_rng(0).standard_normal(1 << 22)

        def work():
            for _ in range(6):
                np.exp(a)

        work()  # warm/page-in
        t0 = time.perf_counter()
        work()
        work()
        seq = time.perf_counter() - t0
        ts = [threading.Thread(target=work) for _ in range(2)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        par = time.perf_counter() - t0
        return round(seq / par, 2)

    modes = {"mc": dict(n_members=2, hosts=True, cfg_kw={}),
             "qbdc": dict(n_members=1, hosts=False,
                          cfg_kw=dict(qbdc_k=qbdc_k))}
    al_users = [make_user(f"u{i}", seed + 10 * i) for i in range(n_users)]
    capacity = host_parallel_speedup()
    _log(f"cnn-fleet workload: {n_users} users x {n_songs} songs, "
         f"mc M=2 / qbdc K={qbdc_k}, q={args_ns.k}, "
         f"{args_ns.al_epochs} AL iterations, {reps} interleaved reps, "
         f"host parallel capacity {capacity}x")

    root = tempfile.mkdtemp(prefix="cnn_fleet_bench_")
    out_modes = {}
    try:
        for mode, spec in modes.items():
            cfg = ALConfig(queries=args_ns.k, epochs=args_ns.al_epochs,
                           mode=mode, seed=seed, ckpt_dtype="float32",
                           gate_host_updates=True, **spec["cfg_kw"])
            # sequential reference (untimed): the parity ground truth
            loop = ALLoop(cfg, retrain_epochs=retrain_epochs)
            seq = []
            for i, data in enumerate(al_users):
                p = os.path.join(root, f"{mode}_seq_{i}")
                os.makedirs(p)
                seq.append(loop.run_user(
                    committee_fn(data, seed + 10 * i, spec["n_members"],
                                 spec["hosts"]), data, p, seed=cfg.seed))
            best = {}
            for rep in range(reps):
                for arm, stack in (("stacked", True), ("per_user", False)):
                    report = FleetReport()
                    sched = FleetScheduler(cfg, report=report,
                                           retrain_epochs=retrain_epochs,
                                           user_timings=False,
                                           batch_window_s=batch_window_s,
                                           stack_cnn=stack)
                    entries = []
                    for i, data in enumerate(al_users):
                        p = os.path.join(root,
                                         f"{mode}_{arm}_{rep}_{i}")
                        os.makedirs(p)
                        entries.append(FleetUser(
                            data.user_id,
                            committee_fn(data, seed + 10 * i,
                                         spec["n_members"], spec["hosts"]),
                            data, p, seed=cfg.seed))
                    t0 = time.perf_counter()
                    recs = sched.run(entries)
                    wall = time.perf_counter() - t0
                    for r, s in zip(recs, seq):
                        assert r["error"] is None, (mode, arm, r["error"])
                        if r["result"]["trajectory"] != s["trajectory"]:
                            raise AssertionError(
                                f"{mode}/{arm} diverged from the "
                                f"sequential trajectory for "
                                f"{r['user']} (rep {rep})")
                    s = report.summary(cohort=n_users, wall_s=wall)
                    prev = best.get(arm)
                    if prev is None or s["users_per_sec"] > \
                            prev["users_per_sec"]:
                        best[arm] = s
            st, pu = best["stacked"], best["per_user"]
            cnn = st["cnn"]
            speedup = round(st["users_per_sec"] / pu["users_per_sec"], 2)
            out_modes[mode] = {
                "users_per_sec": st["users_per_sec"],
                "per_user_users_per_sec": pu["users_per_sec"],
                "speedup_vs_per_user": speedup,
                "mean_device_batch": cnn["mean_device_batch"],
                "occupancy": cnn.get("occupancy"),
                "cnn_dispatches": cnn["dispatches"],
                "per_fn": {fn: cnn[fn] for fn in cnn
                           if isinstance(cnn[fn], dict)},
                "parity_with_sequential": True,  # asserted every rep
            }
            _log(f"[{mode}] stacked {st['users_per_sec']:.3f} users/s vs "
                 f"per-user {pu['users_per_sec']:.3f} ({speedup}x), "
                 f"mean_device_batch {cnn['mean_device_batch']}, "
                 f"parity=True")
    finally:
        shutil.rmtree(root, ignore_errors=True)

    mc = out_modes["mc"]
    print(json.dumps({
        "metric": f"cnn_fleet_users_per_sec_{n_users}u",
        "value": mc["users_per_sec"],
        "unit": "users/s",
        "vs_baseline": mc["speedup_vs_per_user"],
        "mean_device_batch": mc["mean_device_batch"],
        "cohort": n_users,
        "n_songs": n_songs,
        "queries": args_ns.k,
        "al_epochs": args_ns.al_epochs,
        "retrain_epochs": retrain_epochs,
        "qbdc_k": qbdc_k,
        "host_parallel_speedup": capacity,
        "parity_with_sequential": all(
            m["parity_with_sequential"] for m in out_modes.values()),
        "modes": out_modes,
        **_provenance(),
    }))
    return 0


def run_fabric_suite(args_ns) -> int:
    """Multi-host fabric resilience: recovered-users/sec with one worker
    host SIGKILLed mid-run.

    A ``--hosts`` fabric (coordinator in-process, worker subprocesses
    over the shared ``tests/fabric_workload`` synthetic users) serves
    ``--users`` users; the moment the journal shows host h0 admitted a
    user, h0 is SIGKILLed — its in-flight users must resume on the
    survivors from their durable workspaces and its queued users
    re-enqueue in journal order.  Sequential UNFAULTED runs are the
    ground truth: every user must finish with a bit-identical trajectory
    (recovery is exercised, not trusted), and the metric is the faulted
    fabric's users/sec — the price of losing a host mid-run.  Journal
    compaction runs live (small ``compact_bytes``) so the WAL bound is
    exercised under load.  Reps are interleaved best-of (2-vCPU drift
    protocol)."""
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.fabric_workload import (
        make_cfg,
        read_results,
        sequential_baselines,
        user_specs,
    )

    from consensus_entropy_tpu.fleet import FleetReport
    from consensus_entropy_tpu.serve import (
        AdmissionJournal,
        FabricConfig,
        FabricCoordinator,
    )
    from consensus_entropy_tpu.serve.hosts import fabric_paths

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "fabric_worker.py")
    n_users, hosts = args_ns.users, args_ns.hosts
    epochs = args_ns.al_epochs
    cfg = make_cfg("mc", epochs=epochs)
    specs = user_specs(n_users)
    compact_bytes = 1024  # small enough that the run compacts live

    _log(f"fabric workload: {n_users} users x {epochs} AL iterations, "
         f"{hosts} worker hosts, h0 SIGKILLed at its first admission, "
         f"journal compaction at {compact_bytes}B")

    root = tempfile.mkdtemp(prefix="fabric_bench_")
    best = None
    seq_s = float("inf")
    try:
        for rep in range(args_ns.reps):
            ws = _mkdir(root, f"rep{rep}")
            t0 = time.perf_counter()
            seq = sequential_baselines(ws, cfg, specs)
            seq_s = min(seq_s, time.perf_counter() - t0)

            fabric_dir = _mkdir(ws, "fabric")
            journal = AdmissionJournal(
                os.path.join(fabric_dir, "serve_journal.jsonl"),
                compact_bytes=compact_bytes)
            report = FleetReport()

            def spawn(host_id, fabric_dir=fabric_dir, ws=ws):
                log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
                try:
                    return subprocess.Popen(
                        [sys.executable, worker, fabric_dir, host_id, ws,
                         cfg.mode, str(cfg.epochs), str(n_users), "5.0",
                         str(max(2, n_users // hosts))],
                        stdout=log, stderr=subprocess.STDOUT,
                        env={**os.environ, "PYTHONPATH": repo})
                finally:
                    log.close()

            chaos_state = {"killed": False}

            def chaos(coord, chaos_state=chaos_state):
                if chaos_state["killed"]:
                    return
                st = coord.journal.state
                if any(h == "h0" and st.last.get(u) == "admit"
                       for u, h in st.assigned.items()):
                    coord.hosts["h0"].proc.kill()
                    chaos_state["killed"] = True

            coord = FabricCoordinator(
                journal, fabric_dir, FabricConfig(hosts=hosts),
                report=report, on_poll=chaos)
            t0 = time.perf_counter()
            summary = coord.run([u for _, u, _ in specs], spawn)
            wall = time.perf_counter() - t0
            journal.close()

            results = read_results(fabric_dir)
            parity = (sorted(summary["finished"])
                      == [u for _, u, _ in specs]
                      and all(results[u]["error"] is None
                              and results[u]["result"]["trajectory"]
                              == seq[u]["trajectory"]
                              for _, u, _ in specs))
            ups = len(summary["finished"]) / wall
            _log(f"[rep {rep}] fabric {len(summary['finished'])}/"
                 f"{n_users} users in {wall:.1f}s ({ups:.3f} u/s, "
                 f"parity={parity}, killed={chaos_state['killed']}, "
                 f"revocations={summary['revocations']}, "
                 f"reassigned={summary['reassignments']}, "
                 f"compactions={summary['compactions']})")
            if not (parity and chaos_state["killed"]
                    and summary["revocations"] >= 1):
                raise AssertionError(
                    f"fabric rep {rep} lost parity or never exercised "
                    f"the kill: {summary}")
            rec = {"users_per_sec": ups, "wall_s": round(wall, 3),
                   **{k: summary[k] for k in
                      ("revocations", "reassignments", "compactions")},
                   "finished": len(summary["finished"])}
            if best is None or ups > best["users_per_sec"]:
                best = rec
    finally:
        shutil.rmtree(root, ignore_errors=True)

    seq_ups = n_users / seq_s
    print(json.dumps({
        "metric": f"fabric_recovered_users_per_sec_{n_users}u_{hosts}h",
        "value": round(best["users_per_sec"], 4),
        "unit": "users/s",
        # recovered-throughput ratio: a fabric that loses a host mid-run
        # vs the UNFAULTED sequential loop over the same users
        "vs_baseline": round(best["users_per_sec"] / seq_ups, 2),
        "hosts": hosts,
        "sequential_unfaulted_users_per_sec": round(seq_ups, 4),
        "users_done": best["finished"],
        "revocations": best["revocations"],
        "reassignments": best["reassignments"],
        "compactions": best["compactions"],
        "parity_with_sequential": True,
        **_provenance(),
    }))
    return 0


def run_elastic_suite(args_ns) -> int:
    """Elastic fabric control plane: recovered-users/sec + per-host
    stacked-dispatch occupancy, bucket-aware vs least-loaded placement.

    Both arms run the SAME drill per rep: a 2-host ELASTIC fabric
    (``min_hosts=2``, ``max_hosts=3``) over a two-bucket workload
    (pool sizes cycling 30,30,100,100 — two pow2 dispatch buckets), h0
    SIGKILLed at its first admission; the autoscaler must respawn a
    replacement (fresh id, spawn/join journaled) and every user must
    finish bit-identical to unfaulted sequential baselines — parity
    asserted EVERY rep of BOTH arms.  The arms differ only in
    ``FabricConfig.placement``: ``bucket`` co-locates same-bucket users
    so each host's stacked dispatches stay full; ``load`` is the PR 5
    least-loaded rule, which mixes buckets per host and halves dispatch
    occupancy.  Workers write per-host schema-v2 metrics
    (``CETPU_FABRIC_METRICS``); the metric graded is the mean over
    hosts of each host's dispatch occupancy, plus the fleet planner's
    merged edges asserted IDENTICAL on every host that adopted them.
    Interleaved best-of reps (2-vCPU drift protocol)."""
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.fabric_workload import (
        make_cfg,
        read_results,
        sequential_baselines,
        sizes_arg,
        user_specs,
    )

    from consensus_entropy_tpu.obs import export
    from consensus_entropy_tpu.serve import (
        AdmissionJournal,
        FabricConfig,
        FabricCoordinator,
        validate_journal_file,
    )
    from consensus_entropy_tpu.serve.hosts import fabric_paths

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "fabric_worker.py")
    n_users, hosts = args_ns.users, args_ns.hosts
    epochs = args_ns.al_epochs
    cfg = make_cfg("mc", epochs=epochs)
    specs = user_specs(n_users, sizes=[30, 30, 100, 100])

    _log(f"elastic workload: {n_users} users x {epochs} AL iterations "
         f"(pool sizes 30/100 — two dispatch buckets), {hosts} worker "
         f"hosts (min {hosts} / max {hosts + 1}), h0 SIGKILLed at its "
         f"first admission, autoscaler respawn required; arms: "
         f"bucket-aware vs least-loaded placement")

    target_live = max(2, n_users // hosts)

    def run_arm(ws, placement):
        # each arm gets its OWN workspace root: shared workspaces would
        # hand the second arm already-finished users (no dispatches, no
        # placement to measure)
        arm_ws = _mkdir(ws, f"ws_{placement}")
        fabric_dir = _mkdir(ws, f"fabric_{placement}")
        jp = os.path.join(fabric_dir, "serve_journal.jsonl")
        journal = AdmissionJournal(jp)

        def spawn(host_id):
            log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
            try:
                return subprocess.Popen(
                    [sys.executable, worker, fabric_dir, host_id, arm_ws,
                     cfg.mode, str(cfg.epochs), str(n_users), "5.0",
                     str(target_live), sizes_arg(specs)],
                    stdout=log, stderr=subprocess.STDOUT,
                    env={**os.environ, "PYTHONPATH": repo,
                         "CETPU_FABRIC_METRICS": "1"})
            finally:
                log.close()

        chaos_state = {"killed": False}

        def chaos(coord):
            if chaos_state["killed"]:
                return
            st = coord.journal.state
            if any(h == "h0" and st.last.get(u) == "admit"
                   for u, h in st.assigned.items()):
                coord.hosts["h0"].proc.kill()
                chaos_state["killed"] = True

        coord = FabricCoordinator(
            journal, fabric_dir,
            FabricConfig(hosts=hosts, min_hosts=hosts,
                         max_hosts=hosts + 1, placement=placement,
                         planner_epoch=4),
            on_poll=chaos)
        t0 = time.perf_counter()
        summary = coord.run([u for _, u, _ in specs], spawn,
                            pools={u: n for _, u, n in specs})
        wall = time.perf_counter() - t0
        journal.close()

        assert validate_journal_file(jp) == [], \
            f"journal schema violations in the {placement} arm"
        # per-host STACKED-DISPATCH occupancy: how full each host's
        # stacked dispatches ran against its slot capacity
        # (mean_device_batch / target_live, meaned over surviving
        # hosts).  The summary's in-bucket `occupancy` can't see
        # placement — it grades against same-bucket active slots only;
        # a host whose slots hold users of DIFFERENT buckets dispatches
        # thin stacks at in-bucket occupancy 1.0.
        merged = export.merged_summary(fabric_dir)
        widths = [s["mean_device_batch"] / target_live
                  for s in merged["per_host"].values()
                  if s.get("mean_device_batch") is not None]
        occupancy = round(sum(widths) / len(widths), 3) if widths \
            else None
        # the fleet planner's broadcast edges must END identical on
        # every surviving host (the cross-host alignment acceptance:
        # the LAST fleet-adopted record per host — earlier epochs may
        # legitimately differ as the merged sketch grew)
        host_edges = set()
        for h, state in summary["hosts"].items():
            if state == "revoked":
                continue
            last = None
            for rec in export.read_jsonl_tolerant(
                    os.path.join(fabric_dir, f"events_{h}.jsonl")):
                if rec.get("event") == "planner" and rec.get("fleet"):
                    last = tuple(rec.get("edges") or ())
            if last is not None:
                host_edges.add(last)
        assert len(host_edges) <= 1, \
            f"fleet edges diverged across hosts: {host_edges}"
        return {"summary": summary, "wall_s": wall,
                "occupancy": occupancy,
                "fleet_edges": sorted(host_edges),
                "chaos": chaos_state["killed"], "fabric_dir": fabric_dir}

    root = tempfile.mkdtemp(prefix="elastic_bench_")
    best = {"bucket": None, "load": None}
    seq_s = float("inf")
    try:
        for rep in range(args_ns.reps):
            ws = _mkdir(root, f"rep{rep}")
            t0 = time.perf_counter()
            seq = sequential_baselines(ws, cfg, specs)
            seq_s = min(seq_s, time.perf_counter() - t0)
            for placement in ("bucket", "load"):
                arm = run_arm(ws, placement)
                summary = arm["summary"]
                results = read_results(arm["fabric_dir"])
                parity = (sorted(summary["finished"])
                          == sorted(u for _, u, _ in specs)
                          and all(results[u]["error"] is None
                                  and results[u]["result"]["trajectory"]
                                  == seq[u]["trajectory"]
                                  for _, u, _ in specs))
                ups = len(summary["finished"]) / arm["wall_s"]
                _log(f"[rep {rep}] {placement:>6}: "
                     f"{len(summary['finished'])}/{n_users} users in "
                     f"{arm['wall_s']:.1f}s ({ups:.3f} u/s, "
                     f"occupancy={arm['occupancy']}, parity={parity}, "
                     f"spawns={summary['spawns']}, "
                     f"joins={summary['joins']}, "
                     f"migrations={summary['migrations']})")
                if not (parity and arm["chaos"]
                        and summary["revocations"] >= 1
                        and summary["spawns"] >= 1):
                    raise AssertionError(
                        f"elastic {placement} rep {rep} lost parity or "
                        f"never exercised kill+respawn: {summary}")
                rec = {"users_per_sec": ups,
                       "wall_s": round(arm["wall_s"], 3),
                       "occupancy": arm["occupancy"],
                       "fleet_edges": arm["fleet_edges"],
                       **{k: summary[k] for k in
                          ("revocations", "spawns", "joins",
                           "migrations")}}
                prev = best[placement]
                if prev is None or ups > prev["users_per_sec"]:
                    best[placement] = rec
    finally:
        shutil.rmtree(root, ignore_errors=True)

    b, l = best["bucket"], best["load"]
    occ_ratio = (round(b["occupancy"] / l["occupancy"], 2)
                 if b["occupancy"] and l["occupancy"] else None)
    print(json.dumps({
        "metric": f"elastic_recovered_users_per_sec_{n_users}u_{hosts}h",
        "value": round(b["users_per_sec"], 4),
        "unit": "users/s",
        "vs_baseline": round(b["users_per_sec"] / l["users_per_sec"], 2),
        "mean_host_occupancy_bucket": b["occupancy"],
        "mean_host_occupancy_least_loaded": l["occupancy"],
        "occupancy_ratio_bucket_vs_least_loaded": occ_ratio,
        "sequential_unfaulted_users_per_sec":
            round(n_users / seq_s, 4),
        "spawns": b["spawns"], "joins": b["joins"],
        "migrations": b["migrations"],
        "fleet_edges": b["fleet_edges"],
        "parity_with_sequential": True,
        **_provenance(),
    }))
    return 0


def run_drain_suite(args_ns) -> int:
    """Graceful scale-down: checkpoint-FENCED in-flight migration vs
    drain-by-waiting, raced on recovered-users/s and drain latency.

    Both arms run the SAME drill per rep: a 3-host elastic fabric over
    slow workers (a ``pool.score:delay`` rule stretches every iteration
    — values untouched, so parity still binds), with the low-water
    timer FORCED the moment every host holds an in-flight user, so one
    surplus host drains mid-run.  The arms differ only in
    ``FabricConfig.migrate_inflight``:

    - ``fence``: the draining host's in-flight users checkpoint at
      their next iteration boundary and MIGRATE (journaled fence ack →
      committed re-assign) — the host retires as soon as the hand-offs
      land;
    - ``wait``: in-flight users simply FINISH on the draining host (the
      PR 13-shaped baseline: only queued users can move), so retirement
      waits out the slowest session.

    Parity vs unfaulted sequential baselines is asserted on EVERY rep
    of BOTH arms; the fence arm must fence >= 1 user, the wait arm
    exactly 0.  ``drain_latency_s`` is the journal-derived
    ``drain`` → ``drain_done`` wall delta (the time the fleet carries
    the surplus host after deciding to shed it)."""
    import json as json_mod
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.fabric_workload import (
        force_low_water as _flw,
        make_cfg,
        read_results,
        sequential_baselines,
        sizes_arg,
        user_specs,
    )

    from consensus_entropy_tpu.serve import (
        AdmissionJournal,
        FabricConfig,
        FabricCoordinator,
        validate_journal_file,
    )
    from consensus_entropy_tpu.serve.hosts import fabric_paths

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "fabric_worker.py")
    n_users, hosts = args_ns.users, max(args_ns.hosts, 3)
    epochs = args_ns.al_epochs
    cfg = make_cfg("mc", epochs=epochs)
    specs = user_specs(n_users, sizes=[30, 100])
    target_live = max(2, n_users // hosts)

    _log(f"drain workload: {n_users} users x {epochs} AL iterations, "
         f"{hosts} hosts scaling down to {hosts - 1} (forced low-water "
         f"mark once every host is mid-run; workers slowed by a "
         f"pool.score delay rule); arms: checkpoint-fenced in-flight "
         f"migration vs drain-by-waiting")

    def force_low_water(coord):
        _flw(coord, hosts=hosts)

    def drain_stamps(jp):
        """``(t_drain, t_drain_done, t_last)`` wall stamps from the
        journal (a missing ``drain_done`` — the run ended while the
        drain still waited — degrades the latency to the run-end FLOOR
        ``t_last - t_drain``, flagged by ``drain_done=False``)."""
        t0 = t1 = tl = None
        with open(jp, "rb") as f:
            for raw in f:
                try:
                    rec = json_mod.loads(raw.decode("utf-8"))
                except ValueError:
                    continue
                if isinstance(rec.get("t"), (int, float)):
                    tl = rec["t"]
                if rec.get("event") == "drain" and t0 is None:
                    t0 = rec.get("t")
                elif rec.get("event") == "drain_done" and t1 is None:
                    t1 = rec.get("t")
        return t0, t1, tl

    def run_arm(ws, arm):
        arm_ws = _mkdir(ws, f"ws_{arm}")
        fabric_dir = _mkdir(ws, f"fabric_{arm}")
        jp = os.path.join(fabric_dir, "serve_journal.jsonl")
        journal = AdmissionJournal(jp)

        def spawn(host_id):
            log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
            try:
                return subprocess.Popen(
                    [sys.executable, worker, fabric_dir, host_id,
                     arm_ws, cfg.mode, str(cfg.epochs), str(n_users),
                     "5.0", str(target_live), sizes_arg(specs)],
                    stdout=log, stderr=subprocess.STDOUT,
                    env={**os.environ, "PYTHONPATH": repo,
                         "CETPU_FAULTS": "pool.score:delay=0.3@1x-1"})
            finally:
                log.close()

        coord = FabricCoordinator(
            journal, fabric_dir,
            FabricConfig(hosts=hosts, min_hosts=hosts - 1,
                         max_hosts=hosts, scale_down_s=600.0,
                         migrate_inflight=(arm == "fence")),
            on_poll=force_low_water)
        t0 = time.perf_counter()
        summary = coord.run([u for _, u, _ in specs], spawn,
                            pools={u: n for _, u, n in specs})
        wall = time.perf_counter() - t0
        journal.close()
        assert validate_journal_file(jp) == [], \
            f"journal schema violations in the {arm} arm"
        td, tdd, tl = drain_stamps(jp)
        done = tdd is not None
        latency = (round(tdd - td, 3) if done
                   else round(tl - td, 3) if td and tl else None)
        return {"summary": summary, "wall_s": wall,
                "drain_latency_s": latency, "drain_done": done,
                "fabric_dir": fabric_dir}

    root = tempfile.mkdtemp(prefix="drain_bench_")
    best = {"fence": None, "wait": None}
    lat_best = {"fence": None, "wait": None}
    try:
        for rep in range(args_ns.reps):
            ws = _mkdir(root, f"rep{rep}")
            seq = sequential_baselines(ws, cfg, specs)
            for arm in ("fence", "wait"):
                out = run_arm(ws, arm)
                summary = out["summary"]
                results = read_results(out["fabric_dir"])
                parity = (sorted(summary["finished"])
                          == sorted(u for _, u, _ in specs)
                          and all(results[u]["error"] is None
                                  and results[u]["result"]["trajectory"]
                                  == seq[u]["trajectory"]
                                  for _, u, _ in specs))
                ups = len(summary["finished"]) / out["wall_s"]
                _log(f"[rep {rep}] {arm:>5}: "
                     f"{len(summary['finished'])}/{n_users} users in "
                     f"{out['wall_s']:.1f}s ({ups:.3f} u/s, "
                     f"drain_latency={out['drain_latency_s']}s"
                     f"{'' if out['drain_done'] else ' (floor)'}, "
                     f"fences={summary['fences']}, parity={parity})")
                ok_fences = (summary["fences"] >= 1 if arm == "fence"
                             else summary["fences"] == 0)
                if not (parity and summary["drains"] >= 1 and ok_fences
                        and summary["revocations"] == 0):
                    raise AssertionError(
                        f"drain {arm} rep {rep} lost parity or never "
                        f"exercised the drain: {summary}")
                rec = {"users_per_sec": ups,
                       "wall_s": round(out["wall_s"], 3),
                       "drain_latency_s": out["drain_latency_s"],
                       "drain_done": out["drain_done"],
                       **{k: summary[k] for k in
                          ("drains", "fences", "migrations")}}
                prev = best[arm]
                if prev is None or ups > prev["users_per_sec"]:
                    best[arm] = rec
                # the drain-latency pin is best-of SEPARATELY: the
                # fastest retirement each arm achieved (a completed
                # retirement beats any run-end floor)
                def _lat_key(r):
                    return (r["drain_done"],
                            -(r["drain_latency_s"] or 1e9))
                if lat_best[arm] is None \
                        or _lat_key(rec) > _lat_key(lat_best[arm]):
                    lat_best[arm] = rec
    finally:
        shutil.rmtree(root, ignore_errors=True)

    f, w = best["fence"], best["wait"]
    lf, lw = lat_best["fence"], lat_best["wait"]
    lat_ratio = (round(lw["drain_latency_s"] / lf["drain_latency_s"], 2)
                 if lf["drain_latency_s"] and lw["drain_latency_s"]
                 else None)
    print(json.dumps({
        "metric": f"drain_latency_s_{n_users}u_{hosts}h_to_"
                  f"{hosts - 1}h",
        "value": lf["drain_latency_s"],
        "unit": "s",
        "vs_baseline": lat_ratio,
        "drain_latency_s_fence": lf["drain_latency_s"],
        "drain_done_fence": lf["drain_done"],
        "drain_latency_s_wait": lw["drain_latency_s"],
        "drain_done_wait": lw["drain_done"],
        "users_per_sec_fence": round(f["users_per_sec"], 4),
        "users_per_sec_wait": round(w["users_per_sec"], 4),
        "fences": lf["fences"], "migrations": lf["migrations"],
        "parity_with_sequential": True,
        **_provenance(),
    }))
    return 0


def run_remedy_suite(args_ns) -> int:
    """Self-healing remediation vs alert-only, raced on users/sec.

    Both arms run the SAME drill per rep: a 3-host fabric where ONLY h0
    carries a ``pool.score:delay`` rule (one degraded host in an
    otherwise healthy fleet — values untouched, so parity still binds)
    and least-loaded placement splits the users evenly.  The fast hosts
    drain their shares and the slow host's unresolved load becomes a
    sustained placement-skew alert.  The arms differ only in
    ``FabricConfig.remedy``:

    - ``remedy``: the coordinator acts on the sustained alert —
      drain-for-rebalance sheds the slow host's surplus (queued users
      over the drop-ack path, in-flight users over the checkpoint
      fence) onto the idle fast hosts, WITHOUT retiring the host;
    - ``alert``: the alert fires but nothing acts (the PR 15-shaped
      baseline) — every user placed on the slow host grinds to the
      finish there.

    Parity vs unfaulted sequential baselines is asserted on EVERY rep
    of BOTH arms; the remedy arm must journal >= 1 ``remedy`` rebalance
    and migrate >= 1 user, the alert arm exactly 0 of each.
    ``remedy_handoff_s`` is the journal-derived delta from the
    ``remedy`` decision to the last shed user's committed re-assign
    (how long the fleet takes to complete the hand-off it decided)."""
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.fabric_workload import (
        make_cfg,
        read_results,
        sequential_baselines,
        sizes_arg,
        user_specs,
    )

    from consensus_entropy_tpu.obs import export
    from consensus_entropy_tpu.serve import (
        AdmissionJournal,
        FabricConfig,
        FabricCoordinator,
        validate_journal_file,
    )
    from consensus_entropy_tpu.serve.hosts import fabric_paths

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "fabric_worker.py")
    n_users, hosts = args_ns.users, max(args_ns.hosts, 3)
    epochs = args_ns.al_epochs
    cfg = make_cfg("mc", epochs=epochs)
    specs = user_specs(n_users, sizes=[30, 100])
    target_live = max(2, n_users // hosts)

    _log(f"remedy workload: {n_users} users x {epochs} AL iterations, "
         f"{hosts} hosts with ONLY h0 slowed by a pool.score delay "
         f"rule; arms: alert-driven drain-for-rebalance vs alert-only")

    def handoff_stamp(jp):
        """``(t_remedy, t_last_assign)`` wall stamps from the journal:
        the first ``remedy`` decision and the LAST committed
        ``assign`` after it (the shed users landing on new hosts).
        Framed-record tolerant: the journal is CRC-framed since the
        durability PR, so a plain-JSON parse would see no rows."""
        t0 = t1 = None
        for rec in export.read_jsonl_tolerant(jp):
            if rec.get("event") == "remedy" and t0 is None:
                t0 = rec.get("t")
            elif rec.get("event") == "assign" and t0 is not None:
                t1 = rec.get("t")
        return t0, t1

    def run_arm(ws, arm):
        arm_ws = _mkdir(ws, f"ws_{arm}")
        fabric_dir = _mkdir(ws, f"fabric_{arm}")
        jp = os.path.join(fabric_dir, "serve_journal.jsonl")
        journal = AdmissionJournal(jp)

        def spawn(host_id):
            log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
            env = {**os.environ, "PYTHONPATH": repo}
            if host_id == "h0":
                env["CETPU_FAULTS"] = "pool.score:delay=0.5@1x-1"
            try:
                return subprocess.Popen(
                    [sys.executable, worker, fabric_dir, host_id,
                     arm_ws, cfg.mode, str(cfg.epochs), str(n_users),
                     "5.0", str(target_live), sizes_arg(specs)],
                    stdout=log, stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()

        coord = FabricCoordinator(
            journal, fabric_dir,
            FabricConfig(hosts=hosts, min_hosts=hosts, max_hosts=hosts,
                         placement="load", remedy=(arm == "remedy"),
                         remedy_hold_s=0.2, remedy_cooldown_s=600.0,
                         remedy_skew=1))
        t0 = time.perf_counter()
        summary = coord.run([u for _, u, _ in specs], spawn,
                            pools={u: n for _, u, n in specs})
        wall = time.perf_counter() - t0
        journal.close()
        assert validate_journal_file(jp) == [], \
            f"journal schema violations in the {arm} arm"
        tr, ta = handoff_stamp(jp)
        handoff = round(ta - tr, 3) if tr and ta else None
        return {"summary": summary, "wall_s": wall,
                "remedy_handoff_s": handoff, "fabric_dir": fabric_dir}

    root = tempfile.mkdtemp(prefix="remedy_bench_")
    best = {"remedy": None, "alert": None}
    try:
        for rep in range(args_ns.reps):
            ws = _mkdir(root, f"rep{rep}")
            seq = sequential_baselines(ws, cfg, specs)
            for arm in ("remedy", "alert"):
                out = run_arm(ws, arm)
                summary = out["summary"]
                results = read_results(out["fabric_dir"])
                parity = (sorted(summary["finished"])
                          == sorted(u for _, u, _ in specs)
                          and all(results[u]["error"] is None
                                  and results[u]["result"]["trajectory"]
                                  == seq[u]["trajectory"]
                                  for _, u, _ in specs))
                ups = len(summary["finished"]) / out["wall_s"]
                _log(f"[rep {rep}] {arm:>6}: "
                     f"{len(summary['finished'])}/{n_users} users in "
                     f"{out['wall_s']:.1f}s ({ups:.3f} u/s, "
                     f"remedies={summary['remedies']}, "
                     f"migrations={summary['migrations']}, "
                     f"handoff={out['remedy_handoff_s']}s, "
                     f"parity={parity})")
                ok_remedy = (
                    summary["remedies"] >= 1
                    and summary["migrations"] >= 1
                    if arm == "remedy"
                    else summary["remedies"] == 0
                    and summary["migrations"] == 0)
                if not (parity and ok_remedy and summary["drains"] == 0
                        and summary["revocations"] == 0):
                    raise AssertionError(
                        f"remedy {arm} rep {rep} lost parity or the "
                        f"wrong arm remediated: {summary}")
                rec = {"users_per_sec": ups,
                       "wall_s": round(out["wall_s"], 3),
                       "remedy_handoff_s": out["remedy_handoff_s"],
                       **{k: summary[k] for k in
                          ("remedies", "migrations", "fences",
                           "fence_timeouts")}}
                prev = best[arm]
                if prev is None or ups > prev["users_per_sec"]:
                    best[arm] = rec
    finally:
        shutil.rmtree(root, ignore_errors=True)

    r, a = best["remedy"], best["alert"]
    print(json.dumps({
        "metric": f"remedy_users_per_sec_{n_users}u_{hosts}h_slow1",
        "value": round(r["users_per_sec"], 4),
        "unit": "users/s",
        "vs_baseline": round(r["users_per_sec"] / a["users_per_sec"], 2),
        "users_per_sec_remedy": round(r["users_per_sec"], 4),
        "users_per_sec_alert": round(a["users_per_sec"], 4),
        "wall_s_remedy": r["wall_s"], "wall_s_alert": a["wall_s"],
        "remedy_handoff_s": r["remedy_handoff_s"],
        "remedies": r["remedies"], "migrations": r["migrations"],
        "fences": r["fences"],
        "parity_with_sequential": True,
        **_provenance(),
    }))
    return 0


def run_gray_suite(args_ns) -> int:
    """Gray-failure ladder vs skew-only remediation, raced on recovery.

    Both arms run the SAME drill per rep: a 3-host fabric where ONLY h0
    carries ``serve.dispatch:stall=3@1x-1`` (the slow-not-dead wedge:
    EVERY dispatch on h0 holds 3 s — values untouched so parity still
    binds, the process alive and beating its lease) and least-loaded
    placement splits the users evenly.  The arms differ only in which
    remediation plane watches:

    - ``ladder``: ``FabricConfig.gray`` — peer-relative detection
      (step walls, append ages) journals PROBATION off the stall
      evidence itself, then ``gray_drain`` sheds ALL of h0's users
      onto the healthy peers;
    - ``skew``: ``FabricConfig.remedy`` (the PR 16 baseline) — only a
      sustained unresolved-LOAD skew triggers drain-for-rebalance,
      which sheds just the surplus; h0 keeps grinding its remaining
      share through the stall.

    Metrics (journal-``t`` derived, per rep; best-of-reps per arm):

    - ``time_to_recover_s``: first journal record -> the moment NO
      unfinished user is placed on the gray host (the last record
      that empties h0's unresolved set) — detection latency plus the
      completed hand-off;
    - ``interactive_p99_s``: per-user first-assign -> finish latency,
      p99 across users (the users parked behind the stall dominate).

    Parity vs unfaulted sequential baselines is asserted on EVERY rep
    of BOTH arms; the ladder arm must journal >= 1 probation and >= 1
    ``gray_drain``, the skew arm exactly 0 probations and >= 1
    ``remedy``; the ladder journal must REPLAY deterministically (two
    independent folds agree on the probation set, schema clean)."""
    import math
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.fabric_workload import (
        make_cfg,
        read_results,
        sequential_baselines,
        sizes_arg,
        user_specs,
    )

    from consensus_entropy_tpu.obs import export
    from consensus_entropy_tpu.serve import (
        AdmissionJournal,
        FabricConfig,
        FabricCoordinator,
        validate_journal_file,
    )
    from consensus_entropy_tpu.serve.hosts import fabric_paths

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "fabric_worker.py")
    n_users, hosts = args_ns.users, max(args_ns.hosts, 3)
    epochs = args_ns.al_epochs
    cfg = make_cfg("mc", epochs=epochs)
    specs = user_specs(n_users, sizes=[30, 100])
    target_live = max(2, n_users // hosts)

    _log(f"gray workload: {n_users} users x {epochs} AL iterations, "
         f"{hosts} hosts with ONLY h0 stalling 3 s on every dispatch; "
         f"arms: gray ladder (probation+gray_drain) vs skew-only "
         f"remediation")

    def journal_rows(jp):
        # CRC-framed since PR 19: the tolerant reader parses both
        # framed and legacy lines
        return export.read_jsonl_tolerant(jp)

    def recover_stamp(jp):
        """Seconds from the journal's first record to the LAST record
        that left the gray host with zero unfinished users (assign-away
        and finish both clear; a later assign back onto h0 re-opens
        the window, so the stamp is the final transition to empty)."""
        t_first = t_clear = None
        on_h0: set = set()
        for rec in journal_rows(jp):
            t = rec.get("t")
            if t is None:
                continue
            if t_first is None:
                t_first = t
            ev, u = rec.get("event"), rec.get("user")
            prev = len(on_h0)
            if ev == "assign":
                if rec.get("host") == "h0":
                    on_h0.add(u)
                else:
                    on_h0.discard(u)
            elif ev == "finish":
                on_h0.discard(u)
            if prev > 0 and not on_h0:
                t_clear = t
        if t_first is None or t_clear is None:
            return None
        return t_clear - t_first

    def interactive_p99(jp):
        """p99 of per-user first-``assign`` -> ``finish`` latency."""
        t0: dict = {}
        lat: dict = {}
        for rec in journal_rows(jp):
            t, u = rec.get("t"), rec.get("user")
            if t is None or u is None:
                continue
            ev = rec.get("event")
            if ev == "assign":
                t0.setdefault(u, t)
            elif ev == "finish" and u in t0:
                lat[u] = t - t0[u]
        if not lat:
            return None
        ranked = sorted(lat.values())
        return ranked[max(0, math.ceil(0.99 * len(ranked)) - 1)]

    def run_arm(ws, arm):
        arm_ws = _mkdir(ws, f"ws_{arm}")
        fabric_dir = _mkdir(ws, f"fabric_{arm}")
        jp = os.path.join(fabric_dir, "serve_journal.jsonl")
        journal = AdmissionJournal(jp)

        def spawn(host_id):
            log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
            env = {**os.environ, "PYTHONPATH": repo}
            if host_id == "h0":
                env["CETPU_FAULTS"] = "serve.dispatch:stall=3@1x-1"
            try:
                return subprocess.Popen(
                    [sys.executable, worker, fabric_dir, host_id,
                     arm_ws, cfg.mode, str(cfg.epochs), str(n_users),
                     "5.0", str(target_live), sizes_arg(specs)],
                    stdout=log, stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()

        if arm == "ladder":
            fcfg = FabricConfig(
                hosts=hosts, min_hosts=hosts, max_hosts=hosts,
                placement="load", gray=True, gray_ratio=2.5,
                gray_min_s=1.5, gray_hold_s=0.3, gray_drain_s=0.5,
                gray_clear_s=600.0)
        else:
            fcfg = FabricConfig(
                hosts=hosts, min_hosts=hosts, max_hosts=hosts,
                placement="load", remedy=True, remedy_hold_s=0.2,
                remedy_cooldown_s=600.0, remedy_skew=1)
        coord = FabricCoordinator(journal, fabric_dir, fcfg)
        t0 = time.perf_counter()
        summary = coord.run([u for _, u, _ in specs], spawn,
                            pools={u: n for _, u, n in specs})
        wall = time.perf_counter() - t0
        journal.close()
        assert validate_journal_file(jp) == [], \
            f"journal schema violations in the {arm} arm"
        if arm == "ladder":
            # replay determinism: two independent folds of the ladder
            # journal must agree on the probation set, and the gray
            # host must be on it
            folds = []
            for _ in range(2):
                j = AdmissionJournal(jp)
                folds.append(set(j.state.probation))
                j.close()
            assert folds[0] == folds[1] and "h0" in folds[0], \
                f"ladder journal replay diverged: {folds}"
        return {"summary": summary, "wall_s": wall,
                "recover_s": recover_stamp(jp),
                "p99_s": interactive_p99(jp),
                "fabric_dir": fabric_dir}

    root = tempfile.mkdtemp(prefix="gray_bench_")
    best = {"ladder": None, "skew": None}
    try:
        for rep in range(args_ns.reps):
            ws = _mkdir(root, f"rep{rep}")
            seq = sequential_baselines(ws, cfg, specs)
            for arm in ("ladder", "skew"):
                out = run_arm(ws, arm)
                summary = out["summary"]
                results = read_results(out["fabric_dir"])
                parity = (sorted(summary["finished"])
                          == sorted(u for _, u, _ in specs)
                          and all(results[u]["error"] is None
                                  and results[u]["result"]["trajectory"]
                                  == seq[u]["trajectory"]
                                  for _, u, _ in specs))
                _log(f"[rep {rep}] {arm:>6}: "
                     f"{len(summary['finished'])}/{n_users} users in "
                     f"{out['wall_s']:.1f}s (recover="
                     f"{out['recover_s'] and round(out['recover_s'], 2)}"
                     f"s, p99={out['p99_s'] and round(out['p99_s'], 2)}"
                     f"s, probations={summary['probations']}, "
                     f"gray_drains={summary['gray_drains']}, "
                     f"remedies={summary['remedies']}, "
                     f"migrations={summary['migrations']}, "
                     f"parity={parity})")
                ok_arm = (
                    summary["probations"] >= 1
                    and summary["gray_drains"] >= 1
                    and summary["migrations"] >= 1
                    if arm == "ladder"
                    else summary["probations"] == 0
                    and summary["remedies"] >= 1)
                if not (parity and ok_arm and summary["drains"] == 0
                        and summary["revocations"] == 0
                        and out["recover_s"] is not None
                        and out["p99_s"] is not None):
                    raise AssertionError(
                        f"gray {arm} rep {rep} lost parity or the "
                        f"wrong plane remediated: parity={parity}, "
                        f"recover_s={out['recover_s']}, "
                        f"p99_s={out['p99_s']}, {summary}")
                rec = {"wall_s": round(out["wall_s"], 3),
                       "time_to_recover_s": round(out["recover_s"], 3),
                       "interactive_p99_s": round(out["p99_s"], 3),
                       **{k: summary[k] for k in
                          ("probations", "gray_drains", "remedies",
                           "migrations", "fences", "depth_changes")}}
                prev = best[arm]
                if prev is None or rec["time_to_recover_s"] \
                        < prev["time_to_recover_s"]:
                    best[arm] = rec
    finally:
        shutil.rmtree(root, ignore_errors=True)

    lad, skw = best["ladder"], best["skew"]
    print(json.dumps({
        "metric": f"gray_recover_s_{n_users}u_{hosts}h_stall1",
        "value": lad["time_to_recover_s"],
        "unit": "s",
        "vs_baseline": round(skw["time_to_recover_s"]
                             / lad["time_to_recover_s"], 2),
        "time_to_recover_s_ladder": lad["time_to_recover_s"],
        "time_to_recover_s_skew": skw["time_to_recover_s"],
        "interactive_p99_s_ladder": lad["interactive_p99_s"],
        "interactive_p99_s_skew": skw["interactive_p99_s"],
        "wall_s_ladder": lad["wall_s"], "wall_s_skew": skw["wall_s"],
        "probations": lad["probations"],
        "gray_drains": lad["gray_drains"],
        "migrations_ladder": lad["migrations"],
        "remedies_skew": skw["remedies"],
        "ladder_beats_skew_recover": lad["time_to_recover_s"]
        < skw["time_to_recover_s"],
        "ladder_beats_skew_p99": lad["interactive_p99_s"]
        < skw["interactive_p99_s"],
        "replay_deterministic": True,
        "parity_with_sequential": True,
        **_provenance(),
    }))
    return 0


def run_soak_suite(args_ns) -> int:
    """Steady-state soak: a seeded shaped-load trace played WALL-CLOCK
    against a keep-open fabric for >= ``--soak-s`` seconds, graded from
    the run's durable artifacts.

    The trace (``workload.trace``) decides everything up front — MMPP
    (bursty) arrivals stretched to the soak horizon, an interactive/
    batch class mix, bucketed pool sizes, and churn (disconnects that
    ride the journaled evict path, reconnects that resume from the
    workspace) — and is saved to ``trace.jsonl`` first, then LOADED
    back and played (the round-trip is part of the run).  The driver
    (``workload.driver``) is a threaded producer against the
    coordinator's bounded live intake: ``QueueFull`` answered with
    seeded-jitter backoff, every retry counted.  The coordinator runs
    with ``hold_on_burn`` + deliberately tight SLO targets so the
    burn detector has something to grade: sustained p95 burn fires the
    ``slo_headroom`` alert and journals an ``admission_hold`` remedy.

    Graded (``workload.grade``): sustained users/sec over the driver-
    measured wall span, per-class p50/p95/p99 vs the SLO targets, alert
    counts by kind, zero user loss from the journal, schema-valid
    streams — and per-user parity vs uninterrupted sequential
    baselines, asserted.

    The determinism pin: the SAME trace file replays (compressed clock,
    fresh fabric + workspaces) and the grader's ``deterministic``
    section — digest, dispositions, class counts, zero-loss, schema
    verdicts — must be IDENTICAL to the wall-clock run's."""
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.fabric_workload import (
        make_cfg,
        make_data,
        read_results,
        sequential_baselines,
        sizes_arg,
        user_specs,
    )

    from consensus_entropy_tpu.fleet import FleetReport
    from consensus_entropy_tpu.obs.alerts import AlertWatcher
    from consensus_entropy_tpu.obs.status import StatusWriter
    from consensus_entropy_tpu.serve import (
        AdmissionJournal,
        FabricConfig,
        FabricCoordinator,
    )
    from consensus_entropy_tpu.serve.hosts import fabric_paths
    from consensus_entropy_tpu.workload import (
        FabricTarget,
        TraceDriver,
        TraceSpec,
        deterministic_equal,
        generate,
        grade_run,
        load,
        save,
        trace_digest,
    )

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "fabric_worker.py")
    n_users, hosts = args_ns.users, args_ns.hosts
    epochs, soak_s = args_ns.al_epochs, float(args_ns.soak_s)
    cfg = make_cfg("mc", epochs=epochs)
    target_live = max(2, n_users // hosts)
    #: tight per-class SLO targets — chosen so the synthetic AL users'
    #: real end-to-end latencies burn the interactive budget and the
    #: hold/alert plane actually exercises (graded, not asserted)
    slo_s = {"interactive": 5.0, "batch": 30.0}

    pool_dist = args_ns.pool_dist

    def spec_for(seed):
        return TraceSpec(
            seed=seed, n_users=n_users, arrival="mmpp", rate=0.5,
            burst_rate=4.0, burst_dwell_s=5.0,
            class_mix=(("interactive", 0.4), ("batch", 0.6)),
            pool_dist=pool_dist, pool_sizes=(20, 30, 60),
            churn_frac=0.25, churn_delay_s=2.0, reconnect_s=4.0,
            horizon_s=soak_s)

    def sizes_of(tr):
        """The trace's pool draw as the per-user size list (uid order)
        — one size per user, so worker-side ``user_specs`` agrees with
        the trace (and the sequential baselines) exactly."""
        pool_of = {e["user"]: e["pool"] for e in tr.events
                   if e["kind"] == "arrive"}
        return [pool_of[f"u{i}"] for i in range(n_users)]

    # the synthetic GNB committees need every class present in a user's
    # pre-training pool; small trace-drawn pools can miss one for some
    # (seed, size) draws, so scan spec seeds (deterministically — the
    # scan order pins the choice) until every user is trainable
    spec = None
    for seed in range(23, 223):
        cand = spec_for(seed)
        if all(len(set(make_data(100 + i, f"u{i}", n_songs=n)
                       .labels.values())) == 4
               for i, n in enumerate(sizes_of(generate(cand)))):
            spec = cand
            break
    assert spec is not None, "no trainable trace seed in the scan range"

    def play(ws, fabric_dir, tr, time_scale):
        """One fabric run fed by the trace driver; returns
        ``(summary, wall_s, driver_stats, journal_path)``."""
        jp = os.path.join(fabric_dir, "serve_journal.jsonl")
        journal = AdmissionJournal(jp)
        report = FleetReport(
            os.path.join(fabric_dir, "fleet_metrics_fleet.jsonl"))

        def spawn(host_id):
            log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
            env = {**os.environ, "PYTHONPATH": repo,
                   "CETPU_FABRIC_METRICS": "1"}
            env.pop("CETPU_FAULTS", None)
            try:
                return subprocess.Popen(
                    [sys.executable, worker, fabric_dir, host_id, ws,
                     cfg.mode, str(cfg.epochs), str(n_users), "5.0",
                     str(target_live), sizes_arg(specs)],
                    stdout=log, stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()

        coord = FabricCoordinator(
            journal, fabric_dir,
            FabricConfig(hosts=hosts, lease_s=5.0, hold_on_burn=True,
                         admission_hold_s=1.0, remedy_hold_s=2.0,
                         remedy_cooldown_s=10.0,
                         slo_interactive_s=slo_s["interactive"],
                         slo_batch_s=slo_s["batch"]),
            report=report,
            status=StatusWriter(os.path.join(fabric_dir, "status"),
                                "coordinator", interval_s=0.2),
            alerts=AlertWatcher(report))
        driver = TraceDriver(tr, FabricTarget(coord),
                             time_scale=time_scale, backoff_seed=7)
        t0 = time.perf_counter()
        driver.start()
        try:
            summary = coord.run([], spawn, keep_open=True)
        finally:
            assert driver.join(timeout=120.0), "trace driver wedged"
            journal.close()
            report.close()
        wall = time.perf_counter() - t0
        return summary, wall, driver.stats.as_dict(), jp

    root = tempfile.mkdtemp(prefix="soak_bench_")
    try:
        trace_path = os.path.join(root, "trace.jsonl")
        save(generate(spec), trace_path)
        tr = load(trace_path)
        assert trace_digest(tr) == trace_digest(generate(spec)), \
            "trace save -> load round-trip broke the digest"
        sizes = sizes_of(tr)
        specs = user_specs(n_users, sizes=sizes)

        _log(f"soak workload: {n_users} users over {hosts} hosts "
             f"(trace seed {spec.seed}), "
             f"mmpp arrivals stretched to {soak_s:.0f}s, "
             f"churn_frac={spec.churn_frac}, pools={sizes}, "
             f"trace={trace_digest(tr)[:12]}")
        seq = sequential_baselines(_mkdir(root, "ws_seq"), cfg, specs)

        _log("soak leg 1/2: wall-clock shaped-load run")
        summary, wall, drv, jp = play(
            _mkdir(root, "ws_soak"), _mkdir(root, "fabric_soak"),
            tr, 1.0)
        assert wall >= soak_s, \
            f"soak ended early: {wall:.1f}s < {soak_s}s horizon"
        g = grade_run(os.path.join(root, "fabric_soak"),
                      journal_path=jp, trace=tr, slo_s=slo_s,
                      wall_s=wall, driver_stats=drv)
        det, meas = g["deterministic"], g["measured"]
        assert det["zero_loss"], f"lost users: {det['lost_users']}"
        assert det["journal_ok"], meas["journal_errors"]
        assert det["stream_ok"], meas["stream_errors"]
        assert drv["rejected"] == 0, f"driver rejections: {drv}"
        results = read_results(os.path.join(root, "fabric_soak"))
        parity = all(results[u]["error"] is None
                     and results[u]["result"]["trajectory"]
                     == seq[u]["trajectory"] for _, u, _ in specs)
        assert parity, "soak run lost parity vs sequential baselines"
        _log(f"soak: {det['finished']}/{n_users} finished in "
             f"{wall:.1f}s ({meas['users_per_sec']:.3f} u/s), "
             f"holds={summary['holds']} "
             f"disconnects={summary['disconnects']} "
             f"reconnects={summary['reconnects']} "
             f"alerts={meas['alerts']} retries="
             f"{drv['queue_full_retries']}")

        # -- the determinism pin: same trace FILE, compressed clock ----
        _log("soak leg 2/2: compressed replay of the same trace file")
        replay_scale = min(1.0, 15.0 / soak_s)
        summary2, wall2, drv2, jp2 = play(
            _mkdir(root, "ws_replay"), _mkdir(root, "fabric_replay"),
            load(trace_path), replay_scale)
        g2 = grade_run(os.path.join(root, "fabric_replay"),
                       journal_path=jp2, trace=load(trace_path),
                       slo_s=slo_s, wall_s=wall2, driver_stats=drv2)
        if not deterministic_equal(g, g2):
            raise AssertionError(
                f"determinism pin broke: {det} != "
                f"{g2['deterministic']}")
        _log(f"replay at {replay_scale:.2f}x: deterministic section "
             f"identical ({wall2:.1f}s wall, "
             f"holds={summary2['holds']})")
    finally:
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps({
        "metric": f"soak_users_per_sec_{n_users}u_{hosts}h_"
                  f"{int(soak_s)}s"
                  + ("" if pool_dist == "bucket" else f"_{pool_dist}"),
        "value": round(meas["users_per_sec"], 4),
        "unit": "users/s",
        "wall_s": round(wall, 3),
        "horizon_s": soak_s,
        "trace_sha": det["trace_sha"],
        "arrival": spec.arrival,
        "pool_dist": spec.pool_dist,
        "churn_frac": spec.churn_frac,
        "finished": det["finished"],
        "class_counts": det["class_counts"],
        "per_class": meas["per_class"],
        "alerts": meas["alerts"],
        "holds": summary["holds"],
        "disconnects": summary["disconnects"],
        "reconnects": summary["reconnects"],
        "driver": drv,
        "zero_loss": True,
        "parity_with_sequential": True,
        "deterministic_replay_identical": True,
        **_provenance(),
    }))
    return 0


#: the six fused serve-step families the mesh K-sweep pins (qbdc shares
#: mc's graph under a distinct family key; hc_pre is the production hc)
MESH_FUSED_KEYS = ("mc_fused", "qbdc_fused", "wmc_fused", "rand_fused",
                   "hc_pre_fused", "mix_fused")


def run_mesh_child(args_ns) -> int:
    """One arm of the mesh K-sweep, run in its OWN process: the parent
    set ``--xla_force_host_platform_device_count=K`` before this
    interpreter imported jax, so ``jax.devices()`` really has K chips.

    Runs every fused serve-step family over one ≥100k-row pool —
    K > 1 through ``parallel.pool_mesh`` (NamedSharding in/out, masks
    donated, the reveal scatter updating the sharded persistent probs
    buffer in place), K == 1 through the UNSHARDED production family —
    and prints one JSON line with per-mode steps/sec plus a selection
    DIGEST: sha256 over every iteration's 2·k selection scalars (the
    one sanctioned host pull).  The parent asserts the digest bit-equal
    across the whole sweep."""
    import hashlib
    import os

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    # sharded PRNG must draw the same stream as the single-device arm
    jax.config.update("jax_threefry_partitionable", True)

    from consensus_entropy_tpu.ops import scoring
    from consensus_entropy_tpu.ops.scoring import selection_scalars
    from consensus_entropy_tpu.parallel import pool_mesh
    from consensus_entropy_tpu.parallel.mesh import POOL_AXIS

    kdev = int(args_ns.mesh_child)
    n, m, c, k = args_ns.pool, 8, args_ns.classes, args_ns.k
    warm, iters = 2, int(args_ns.mesh_iters)
    assert len(jax.devices()) >= kdev, \
        f"child wanted {kdev} devices, has {len(jax.devices())}"
    if kdev > 1:
        mesh = pool_mesh.make_pool_mesh_for(kdev)
        fns = pool_mesh.make_sharded_step_fns(mesh, k=k)
        scatter = pool_mesh.sharded_scatter_rows(mesh)

        def put(x, spec):
            return jax.device_put(x, NamedSharding(mesh, spec))
    else:
        fns = scoring.make_scoring_fns(k=k)
        scatter = jax.jit(pool_mesh._scatter_rows_sharded_impl,
                          donate_argnums=0)

        def put(x, spec):
            return jax.device_put(x)

    rng = np.random.default_rng(1234)
    probs0 = rng.random((m, n, c), dtype=np.float32)
    probs0 /= probs0.sum(-1, keepdims=True)
    hc_freq0 = rng.random((n, c), dtype=np.float32)
    hc_freq0 /= hc_freq0.sum(-1, keepdims=True)
    hc_ent0 = (-np.sum(hc_freq0 * np.log(hc_freq0), axis=-1)
               ).astype(np.float32)
    weights = put((rng.random(m) + 0.5).astype(np.float32), P())
    hc_freq = put(hc_freq0, P(POOL_AXIS, None))
    hc_ent = put(hc_ent0, P(POOL_AXIS))
    base_key = jax.random.PRNGKey(7)

    out_modes = {}
    for fn_key in MESH_FUSED_KEYS:
        # fresh persistent state per mode: donated masks, and (for the
        # probs modes) the sharded persistent probs buffer the reveal
        # scatter mutates in place each iteration
        pool_mask = put(np.ones(n, bool), P(POOL_AXIS))
        hc_mask = put(np.ones(n, bool), P(POOL_AXIS))
        probs = put(probs0.copy(), P(None, POOL_AXIS, None))
        digest = hashlib.sha256()

        def step(it, fn_key=fn_key):
            nonlocal pool_mask, hc_mask, probs
            if fn_key in ("mc_fused", "qbdc_fused", "wmc_fused",
                          "mix_fused"):
                rr = np.random.default_rng(1000 + it)
                rows = rr.integers(0, n, size=k).astype(np.int32)
                block = rr.random((m, k, c), dtype=np.float32)
                block /= block.sum(-1, keepdims=True)
                probs = scatter(probs, rows, block)
            if fn_key in ("mc_fused", "qbdc_fused"):
                r = fns[fn_key](probs, pool_mask)
            elif fn_key == "wmc_fused":
                r = fns[fn_key](probs, pool_mask, weights)
            elif fn_key == "rand_fused":
                r = fns[fn_key](jax.random.fold_in(base_key, it),
                                pool_mask)
            elif fn_key == "hc_pre_fused":
                r = fns[fn_key](hc_ent, hc_mask, pool_mask)
            else:
                r = fns[fn_key](probs, pool_mask, hc_freq, hc_mask)
            pool_mask = r.pool_mask
            if r.hc_mask is not None:
                hc_mask = r.hc_mask
            # the one sanctioned per-iteration host pull: 2·k scalars
            digest.update(selection_scalars(r.values).tobytes())
            digest.update(selection_scalars(r.indices).tobytes())

        for it in range(warm):
            step(it)
        t0 = time.perf_counter()
        for it in range(warm, warm + iters):
            step(it)
        dt = time.perf_counter() - t0
        out_modes[fn_key] = {
            "steps_per_sec": round(iters / dt, 4),
            "digest": digest.hexdigest()}

    print(json.dumps({"k": kdev, "devices": len(jax.devices()),
                      "pid": os.getpid(), "modes": out_modes}))
    return 0


def run_mesh_suite(args_ns) -> int:
    """Pool-axis mesh serving acceptance (ISSUE 18): one worker, K
    simulated devices, pool >= 100k.  Each K in ``--mesh-sweep`` runs as
    its own subprocess (K virtual CPU devices via
    ``--xla_force_host_platform_device_count``); all six fused modes run
    a serve-step loop with the reveal scatter feeding the sharded
    persistent probs buffer, and the per-iteration selection digest is
    asserted BIT-EQUAL to the unsharded K=1 arm on every rep before any
    throughput is reported.  Redirect stdout to ``BENCH_mesh_r<N>.json``
    to commit the K-sweep artifact."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    sweep = sorted(set(int(x) for x in args_ns.mesh_sweep))
    if 1 not in sweep:
        sweep = [1] + sweep  # the unsharded parity reference arm
    reps = args_ns.reps
    _log(f"mesh sweep: K={sweep}, pool={args_ns.pool}, "
         f"k={args_ns.k}, {args_ns.mesh_iters} fused steps/mode, "
         f"{reps} reps (interleaved)")

    def child(kdev):
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo}
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={kdev}"])
        cmd = [sys.executable, os.path.abspath(__file__),
               "--suite", "mesh", "--mesh-child", str(kdev),
               "--pool", str(args_ns.pool), "--k", str(args_ns.k),
               "--classes", str(args_ns.classes),
               "--mesh-iters", str(args_ns.mesh_iters)]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              env=env, timeout=1800)
        if proc.returncode != 0:
            raise AssertionError(
                f"mesh child K={kdev} failed:\n{proc.stdout[-2000:]}"
                f"\n{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    best: dict = {kdev: {} for kdev in sweep}
    reference = None  # mode -> digest, from the FIRST K=1 rep
    for rep in range(reps):
        for kdev in sweep:  # interleaved per the 2-vCPU drift protocol
            r = child(kdev)
            assert r["devices"] >= kdev, r
            if reference is None:
                reference = {fn: d["digest"]
                             for fn, d in r["modes"].items()}
            for fn, d in r["modes"].items():
                assert d["digest"] == reference[fn], \
                    (f"mesh parity broke: K={kdev} rep={rep} {fn} "
                     f"digest {d['digest'][:12]} != unsharded "
                     f"{reference[fn][:12]}")
                cur = best[kdev].get(fn)
                if cur is None or d["steps_per_sec"] > cur:
                    best[kdev][fn] = d["steps_per_sec"]
            _log(f"rep {rep}: K={kdev} parity ok, mc_fused "
                 f"{r['modes']['mc_fused']['steps_per_sec']:.3f} "
                 f"steps/s")

    kmax = sweep[-1]
    print(json.dumps({
        "metric": f"mesh_fused_steps_per_sec_{args_ns.pool}n_"
                  f"k{kmax}d",
        "value": best[kmax]["mc_fused"],
        "unit": "steps/s",
        "pool": args_ns.pool,
        "top_k": args_ns.k,
        "iters_per_mode": args_ns.mesh_iters,
        "sweep": {str(kdev): best[kdev] for kdev in sweep},
        "scaling_vs_1d": {
            str(kdev): round(best[kdev]["mc_fused"]
                             / best[1]["mc_fused"], 3)
            for kdev in sweep},
        "modes": list(MESH_FUSED_KEYS),
        "parity_bit_exact_all_reps": True,
        # the sweep's K virtual devices all share ONE host CPU
        # (--xla_force_host_platform_device_count), so steps/sec here
        # measures partition OVERHEAD, not chip scaling — the artifact
        # pins the bit-exact parity contract; throughput scaling needs
        # real chips
        "devices_simulated_on_one_host": True,
        **_provenance(),
    }))
    return 0


def run_durability_suite(args_ns) -> int:
    """CRC-framed vs legacy journal overhead (ISSUE 19 acceptance).

    Pure host, no device work: the same mixed admission workload
    (enqueue/admit/finish over a recycled user set) is appended through
    ``AdmissionJournal(frame=True)`` (the ``w1 <crc32> <json>`` default)
    and ``frame=False`` (the pre-PR legacy plain-JSON arm), interleaved
    per rep with best-of-reps throughput (the 2-vCPU drift protocol).
    Replay parity is asserted EVERY rep — both arms must reconstruct
    bit-identical state dicts and validate schema-clean — before any
    throughput is reported.  Acceptance: the framed arm's append path
    costs < 5% (CRC32 of the payload bytes is noise next to the
    per-record fsync).  Redirect stdout to ``BENCH_durability_r<N>.json``
    to commit the artifact."""
    import os
    import tempfile
    import time

    from consensus_entropy_tpu.serve.journal import (
        AdmissionJournal,
        validate_journal_file,
    )

    n = 5000
    users = 50
    reps = args_ns.reps
    root = tempfile.mkdtemp(prefix="bench_durability_")
    _log(f"durability: {n} appends x {reps} reps, framed vs legacy, "
         f"parity every rep")

    def workload(journal):
        for i in range(n):
            u = f"u{i % users}"
            ev = ("enqueue", "admit", "finish")[i % 3]
            journal.append(ev, u)

    best = {"framed": {"append": 0.0, "replay": 0.0},
            "legacy": {"append": 0.0, "replay": 0.0}}
    for rep in range(reps):
        states = {}
        for arm, frame in (("framed", True), ("legacy", False)):
            jp = os.path.join(root, f"j_{rep}_{arm}.jsonl")
            t0 = time.perf_counter()
            with AdmissionJournal(jp, frame=frame) as j:
                workload(j)
            best[arm]["append"] = max(
                best[arm]["append"], n / (time.perf_counter() - t0))
            t0 = time.perf_counter()
            states[arm] = AdmissionJournal(jp).state.to_dict()
            best[arm]["replay"] = max(
                best[arm]["replay"], n / (time.perf_counter() - t0))
            assert validate_journal_file(jp) == [], arm
        assert states["framed"] == states["legacy"], \
            f"rep {rep}: framed and legacy replay diverged"
        _log(f"rep {rep}: parity ok, framed "
             f"{best['framed']['append']:.0f} appends/s, legacy "
             f"{best['legacy']['append']:.0f}")

    overhead = (best["legacy"]["append"] / best["framed"]["append"]
                - 1.0) * 100.0
    assert overhead < 5.0, \
        (f"CRC framing costs {overhead:.1f}% on the append path "
         f"(acceptance < 5%)")
    print(json.dumps({
        "metric": "journal_framed_appends_per_sec",
        "value": round(best["framed"]["append"], 1),
        "unit": "appends/s",
        "records": n,
        "reps": reps,
        "framed": {k: round(v, 1) for k, v in best["framed"].items()},
        "legacy": {k: round(v, 1) for k, v in best["legacy"].items()},
        "append_overhead_pct": round(overhead, 2),
        "replay_overhead_pct": round(
            (best["legacy"]["replay"] / best["framed"]["replay"] - 1.0)
            * 100.0, 2),
        "acceptance_append_overhead_lt_pct": 5.0,
        "parity_bit_exact_all_reps": True,
        **_provenance(),
    }))
    return 0


def _mkdir(root, name):
    import os

    p = os.path.join(root, name)
    os.makedirs(p)
    return p


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--suite", choices=("linear", "cnn", "retrain", "fleet",
                                        "serve", "serve-fused", "slo",
                                        "serve-faults", "fabric", "elastic",
                                        "drain", "remedy", "soak", "mesh",
                                        "qbdc", "cnn-fleet", "obs",
                                        "durability", "gray"),
                    default="linear",
                    help="linear: the north-star fused pool scoring; cnn: "
                         "Flax ShortChunkCNN committee inference "
                         "(BASELINE configs[3]); retrain: vmapped committee "
                         "retraining vs the sequential member loop; fleet: "
                         "multi-user AL users/sec vs the sequential loop; "
                         "serve: continuous-batching admission + bucketed "
                         "padding vs fleet cohorts on a skewed workload; "
                         "serve-fused: the fused serve step (device-"
                         "resident pool state, in-graph select/reveal/"
                         "mask) vs --no-fuse-step on one bucketed "
                         "workload — h2d bytes + device calls per "
                         "iteration, parity asserted every rep; "
                         "slo: SLO-aware admission planner (adaptive "
                         "quantile-sketch bucket edges, priority "
                         "classes, predictive dispatch holds) vs the "
                         "fixed-window arm on the tail-heavy serve "
                         "workload — mean bucket occupancy, users/sec, "
                         "per-class admission→finish p95, parity "
                         "asserted every rep; "
                         "serve-faults: recovered-users/sec under a "
                         "fault-injected flaky user mix (watchdog, "
                         "backoff re-admission, circuit breaker); "
                         "fabric: recovered-users/sec of a multi-host "
                         "fabric with one worker SIGKILLed mid-run "
                         "(journal failover + compaction); "
                         "elastic: the elastic control plane — a worker "
                         "SIGKILLed mid-run with the autoscaler "
                         "respawning a replacement, bucket-aware vs "
                         "least-loaded placement raced on per-host "
                         "stacked-dispatch occupancy, merged planner "
                         "edges asserted identical across hosts, parity "
                         "asserted every rep of both arms; "
                         "drain: graceful scale-down — checkpoint-"
                         "fenced in-flight migration vs drain-by-"
                         "waiting on a 3-host fabric shedding one slow "
                         "host, recovered-users/sec + journal-derived "
                         "drain latency, parity asserted every rep of "
                         "both arms; remedy: the self-healing plane — "
                         "alert-driven drain-for-rebalance off ONE "
                         "degraded host vs alert-only, users/sec + "
                         "journal-derived remedy hand-off latency, "
                         "parity asserted every rep of both arms; "
                         "soak: steady-state shaped load — a seeded "
                         "trace (mmpp arrivals, class mix, bucketed "
                         "pools, churn) played wall-clock against a "
                         "keep-open fabric for --soak-s seconds, "
                         "graded for sustained users/sec + per-class "
                         "p50/p95/p99 vs SLO + alert counts, zero "
                         "loss + parity asserted, then the SAME trace "
                         "file replayed compressed and the grader's "
                         "deterministic section asserted identical; "
                         "mesh: pool-axis mesh serving — each K in "
                         "--mesh-sweep runs the six fused serve-step "
                         "modes over a >=100k pool in its own "
                         "subprocess with K virtual devices "
                         "(NamedSharding families, donated masks, "
                         "sharded reveal scatter), steps/sec per "
                         "(K, mode) with the per-iteration selection "
                         "digest asserted bit-equal to the unsharded "
                         "K=1 arm on every rep; "
                         "durability: CRC-framed vs legacy journal "
                         "append/replay throughput (pure host), replay "
                         "parity asserted every rep, acceptance < 5%% "
                         "append overhead; "
                         "qbdc: "
                         "dropout-committee scoring (K-sweep) + users/sec "
                         "+ per-user memory vs the stored-committee mc "
                         "path; cnn-fleet: users/sec + mean_device_batch "
                         "of a same-bucket CNN cohort under the stacked "
                         "cross-user device path vs per-user CNN "
                         "dispatch (mc + qbdc, parity asserted); obs: "
                         "span-tracing overhead — traced vs --no-trace "
                         "serve runs, interleaved best-of-reps, parity "
                         "asserted every rep, spans/metrics schema-"
                         "validated every traced rep (acceptance: "
                         "overhead <= 3%)")
    ap.add_argument("--members", type=int, default=None,
                    help="committee size (default: 16 linear / 5 cnn)")
    ap.add_argument("--pool", type=int, default=None,
                    help="pool size (default: 100000 linear / 48 cnn)")
    ap.add_argument("--frames", type=int, default=4)
    ap.add_argument("--features", type=int, default=260)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--mode", choices=("mc", "hc", "mix"), default="mc",
                    help="acquisition chain to benchmark (BASELINE configs "
                         "0-2); hc has no committee in the loop")
    ap.add_argument("--arch", choices=("vgg", "res", "harm", "se1d", "musicnn"),
                    default="vgg",
                    help="CNN trunk family for the cnn suite")
    ap.add_argument("--gate-weights", choices=("trained", "random"),
                    default="trained",
                    help="cnn suite: evaluate the bf16 probability-parity "
                         "gate on briefly fit_many-trained members "
                         "(production regime) or on random init (quick)")
    ap.add_argument("--gate-train-epochs", type=int, default=10,
                    help="epochs of gate pretraining (cnn suite, "
                         "--gate-weights trained)")
    ap.add_argument("--impl", choices=("auto", "xla", "pallas"),
                    default="auto")
    ap.add_argument("--tile-n", type=int, default=512,
                    help="pallas pool tile (pool rows per grid step)")
    ap.add_argument("--tile-sweep", type=int, nargs="*", default=None,
                    help="extra pallas pool tiles to race alongside "
                         "--tile-n (each costs one Mosaic compile)")
    ap.add_argument("--fuse-topk", action="store_true",
                    help="rank queries inside the pallas kernel")
    ap.add_argument("--chain", type=int, default=150,
                    help="iterations per in-program timing window")
    ap.add_argument("--retrain-epochs", type=int, default=8,
                    help="epochs per timed window (retrain suite)")
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--cpu-reps", type=int, default=3)
    ap.add_argument("--fleet", type=int, nargs="+", default=[4],
                    help="fleet suite: cohort sizes N to sweep")
    ap.add_argument("--users", type=int, default=8,
                    help="fleet suite: total synthetic users per run")
    ap.add_argument("--al-epochs", type=int, default=3,
                    help="fleet suite: AL iterations per user")
    ap.add_argument("--host-workers", type=int, default=None,
                    help="fleet suite: host worker pool size "
                         "(default min(N, cpus, 8))")
    ap.add_argument("--reps", type=int, default=3,
                    help="fleet suite: timing repetitions; best (min "
                         "wall) is reported for both sides")
    ap.add_argument("--hosts", type=int, default=2,
                    help="fabric suite: worker host processes")
    ap.add_argument("--soak-s", type=float, default=60.0,
                    help="soak suite: trace horizon — the last arrival "
                         "lands here, so the shaped-load run sustains "
                         "at least this many wall seconds (default 60)")
    ap.add_argument("--qbdc-sweep", type=int, nargs="+",
                    default=[8, 20, 64],
                    help="qbdc suite: dropout-committee widths K to sweep "
                         "against the stored-committee mc baseline")
    ap.add_argument("--mesh-sweep", type=int, nargs="+",
                    default=[1, 2, 4, 8],
                    help="mesh suite: simulated device counts K to sweep; "
                         "1 (the unsharded parity reference) is always "
                         "included")
    ap.add_argument("--mesh-iters", type=int, default=20,
                    help="mesh suite: timed fused serve steps per mode "
                         "per arm (plus 2 warmup steps, digested too)")
    ap.add_argument("--mesh-child", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--pool-dist", choices=("bucket", "skew", "cycle"),
                    default="bucket",
                    help="soak suite: trace pool-size distribution — "
                         "bucket (uniform over the bucket sizes), skew "
                         "(80%% of users pile onto ONE seeded hot size: "
                         "the adversarial single-bucket row), cycle "
                         "(per-user growth re-bucketing mid-soak)")
    args_ns = ap.parse_args(argv)

    import jax

    if args_ns.suite == "fleet":
        # fleet reuses --pool as songs-per-user (default 150 inside)
        return run_fleet_suite(args_ns)
    if args_ns.suite == "serve-fused":
        return run_serve_fused_suite(args_ns)
    if args_ns.suite == "obs":
        # traced vs untraced serve over --users; --pool is songs per user
        return run_obs_suite(args_ns)
    if args_ns.suite == "serve":
        # serve reuses --pool as the SMALL pool size (every 4th user 4x)
        return run_serve_suite(args_ns)
    if args_ns.suite == "slo":
        # same skewed sizing as serve; every 3rd user is interactive,
        # target_live is the LAST --fleet value
        return run_slo_suite(args_ns)
    if args_ns.suite == "serve-faults":
        # same skewed sizing as serve; every 3rd user is flaky
        return run_serve_faults_suite(args_ns)
    if args_ns.suite == "fabric":
        # multi-host: --users over --hosts workers, h0 killed mid-run
        return run_fabric_suite(args_ns)
    if args_ns.suite == "elastic":
        # elastic control plane: kill + autoscaler respawn, placement
        # arms raced on per-host dispatch occupancy
        return run_elastic_suite(args_ns)
    if args_ns.suite == "drain":
        # graceful scale-down: fenced migration vs drain-by-waiting
        return run_drain_suite(args_ns)
    if args_ns.suite == "remedy":
        # self-healing: alert-driven rebalance off one slow host vs
        # alert-only
        return run_remedy_suite(args_ns)
    if args_ns.suite == "gray":
        # gray failure: the detection+ladder plane vs the PR 16
        # skew-only remediation under one stalling host
        return run_gray_suite(args_ns)
    if args_ns.suite == "soak":
        # steady-state: a seeded shaped-load trace played wall-clock
        # for --soak-s seconds, plus the compressed determinism replay
        return run_soak_suite(args_ns)
    if args_ns.suite == "durability":
        # pure host: framed vs legacy journal, no device work at all
        return run_durability_suite(args_ns)
    if args_ns.suite == "mesh":
        if args_ns.mesh_child is not None:
            args_ns.pool = 100_000 if args_ns.pool is None else args_ns.pool
            return run_mesh_child(args_ns)
        # K-sweep of the sharded fused serve step, one subprocess per
        # arm so each gets its own forced virtual-device count
        args_ns.pool = 100_000 if args_ns.pool is None else args_ns.pool
        return run_mesh_suite(args_ns)
    if args_ns.suite == "qbdc":
        # dropout committee vs stored committee; --pool is songs per user,
        # --members the stored-committee size (default 20, the paper's)
        return run_qbdc_suite(args_ns)
    if args_ns.suite == "cnn-fleet":
        # CNN cohort stacking vs per-user dispatch; --pool is songs per
        # user (default 120), --users the same-bucket cohort size
        return run_cnn_fleet_suite(args_ns)
    if args_ns.suite == "cnn":
        # cnn-suite defaults: 5 members (paper committee), 48 crops per
        # pass — the first conv block's activations are ~75 MB per
        # member-crop, so member*crop batches beyond ~300 exceed the 16 GB
        # HBM of one v5e chip.  Explicit flags are honored.
        args_ns.members = 5 if args_ns.members is None else args_ns.members
        args_ns.pool = 48 if args_ns.pool is None else args_ns.pool
        return run_cnn_suite(args_ns)
    if args_ns.suite == "retrain":
        return run_retrain_suite(args_ns)
    args_ns.members = 16 if args_ns.members is None else args_ns.members
    args_ns.pool = 100_000 if args_ns.pool is None else args_ns.pool

    if args_ns.mode == "hc":
        # no committee in the hc loop (amg_test.py:449-455): don't generate
        # the ~GB member-input pool it would never read
        x = w = b = None
    else:
        x, w, b = make_inputs(args_ns.members, args_ns.pool, args_ns.frames,
                              args_ns.features, args_ns.classes)
    _log(f"devices: {jax.devices()}")
    _log(f"pool {args_ns.pool} x {args_ns.frames} frames x "
         f"{args_ns.features} feats, {args_ns.members} members, k={args_ns.k}")

    hc_freq = (make_hc_table(args_ns.pool, args_ns.classes)
               if args_ns.mode in ("hc", "mix") else None)

    # -- CPU reference-structure baseline + oracle ------------------------
    # untimed warm-up rep: the first call pays the scipy import (~2 s),
    # which would dominate the cheap hc chain at --cpu-reps 1
    ent_cpu, idx_cpu = cpu_reference_iteration(x, w, b, args_ns.k,
                                               args_ns.mode, hc_freq)
    cpu_times = []
    for _ in range(args_ns.cpu_reps):
        t0 = time.perf_counter()
        ent_cpu, idx_cpu = cpu_reference_iteration(x, w, b, args_ns.k,
                                                   args_ns.mode, hc_freq)
        cpu_times.append(time.perf_counter() - t0)
    cpu_ms = float(np.median(cpu_times) * 1e3)
    _log(f"cpu median over {args_ns.cpu_reps} reps: {cpu_ms:.1f} ms")

    # -- device implementations -------------------------------------------
    impls = {}
    if args_ns.impl in ("auto", "xla", "pallas"):
        # the pallas run keeps the xla build too: the committed artifact
        # must carry the comparison, not just the kernel's own number
        impls["xla"] = build_xla_impl(x, w, b, args_ns.k, args_ns.mode,
                                      hc_freq)
        if args_ns.impl == "auto" and args_ns.mode == "mc":
            # race the flat-GEMM layout of the same math; XLA tiles the two
            # differently and which wins can change with pool geometry
            impls["xla-flat"] = build_xla_impl(x, w, b, args_ns.k, "mc",
                                               None, flat_gemm=True)
    if args_ns.impl == "pallas" and args_ns.mode != "mc":
        _log("[pallas] the Mosaic kernel implements the mc chain only")
        return 1
    if args_ns.impl == "pallas":
        # The Mosaic kernel is experimental/opt-in: at north-star scale it
        # only ties XLA (BENCH_r01: xla 1.365 ms vs pallas 1.439 ms) while
        # costing ~92 s of Mosaic compile, so `auto` no longer builds it.
        # See consensus_entropy_tpu/experimental/__init__.py.
        devices = jax.devices()
        if devices[0].platform == "tpu":
            impls["pallas"] = build_pallas_impl(x, w, b, args_ns.k,
                                                args_ns.tile_n,
                                                args_ns.fuse_topk)
            if not args_ns.fuse_topk:
                # race the in-kernel top-k variant too (single- and multi-
                # chip alike); which wins depends on pool size vs the XLA
                # sort cost.
                impls["pallas-fusedtopk"] = build_pallas_impl(
                    x, w, b, args_ns.k, args_ns.tile_n, True)
            for tile in (args_ns.tile_sweep or []):
                if tile != args_ns.tile_n:
                    impls[f"pallas-tile{tile}"] = build_pallas_impl(
                        x, w, b, args_ns.k, tile, args_ns.fuse_topk)
        else:
            _log(f"[pallas] skipped: Mosaic kernels need TPU devices "
                 f"(found {devices[0].platform})")
            _log("nothing to run for --impl pallas on this host")
            return 1

    results = {}
    failures = {}
    for name, (iargs, ifn) in impls.items():
        try:
            if not check_parity(name, iargs, ifn, ent_cpu, idx_cpu,
                                args_ns.k, n_valid=args_ns.pool):
                _log(f"[{name}] PARITY FAILURE — implementation excluded")
                failures[name] = "parity failure"
                continue
            results[name] = time_device_impl(name, iargs, ifn,
                                             chain=args_ns.chain,
                                             trials=args_ns.trials)
        except Exception as e:
            # a variant that fails to COMPILE (e.g. a pallas tile past the
            # VMEM ceiling) is a data point, not a reason to lose the
            # whole artifact
            msg = failure_message(e)
            _log(f"[{name}] FAILED: {msg}")
            failures[name] = msg

    if not results:
        _log("every candidate implementation failed (parity or compile) — "
             "emitting the failure record")
        print(json.dumps({
            "metric": f"al_pool_scoring_latency_"
                      f"{args_ns.members}m_{args_ns.pool}",
            "value": None, "unit": "ms", "vs_baseline": None,
            "impl_failures": failures, **_provenance()}))
        return 1

    extra = {}
    if args_ns.mode == "hc":
        # Loop-body floor probe, measured IN-PROCESS right next to the hc
        # chain (tunnel latency drifts run-to-run): the same chained-window
        # harness timing a near-empty body on the same (N,) operand.  hc's
        # ms/iter minus this floor is the masked top-k's actual compute —
        # the windows are fori_loop-chained, so there is no per-iteration
        # host dispatch to subtract, only the loop/body overhead.
        import jax.numpy as jnp

        from consensus_entropy_tpu.ops.scoring import ScoreResult

        ent_args = impls["xla"][0]

        def floor_fn(args_f, eps):
            ent, _mask = args_f
            probe = ent[:1] + eps
            return ScoreResult(ent, probe, jnp.zeros(1, jnp.int32))

        floor_ms = time_device_impl("hc-loop-floor", ent_args, floor_fn,
                                    chain=args_ns.chain,
                                    trials=args_ns.trials)
        extra["loop_floor_ms"] = round(floor_ms, 3)
        # r04 semantic change vs BENCH_hc_r02/r03: the device side now
        # times the PRODUCTION per-iteration work (masked top-k over
        # entropies precomputed once at acquirer init); the cpu baseline
        # keeps the reference's per-iteration entropy+argsort.  Flagged
        # here so cross-artifact readers don't attribute the drop to the
        # kernel alone.
        extra["hc_semantics"] = "topk_over_precomputed_entropy_r04"

    best = min(results, key=results.get)
    dev_ms = results[best]
    _log(f"best impl: {best} ({dev_ms:.3f} ms/iter)")
    if "loop_floor_ms" in extra:
        # the committed headline ratio divides by a dispatch/loop-floor-
        # bound total; the floor-corrected ratio divides by the op's
        # MARGINAL compute (total - measured floor) — publish both so the
        # first number a reader sees carries its own correction
        marginal = dev_ms - extra["loop_floor_ms"]
        if marginal > 0:
            extra["vs_baseline_floor_corrected"] = round(cpu_ms / marginal,
                                                         1)
        else:
            # the floor probe and the timed chain are separate runs over a
            # link that drifts; when the probe measures >= the total, the
            # marginal is unresolvable this run — say so, never publish a
            # clamped garbage ratio
            extra["vs_baseline_floor_corrected"] = None
            extra["floor_exceeds_total"] = True

    mode_tag = "" if args_ns.mode == "mc" else f"{args_ns.mode}_"
    print(json.dumps({
        "metric": f"al_pool_scoring_latency_{mode_tag}"
                  f"{args_ns.members}m_{args_ns.pool}",
        "value": round(dev_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / dev_ms, 1),
        # every parity-passing implementation's ms/iter: the race itself
        # is the evidence (which impl won, by how much), not just the
        # winner's number
        "impls": {k: round(v, 3) for k, v in sorted(results.items())},
        "best_impl": best,
        **({"impl_failures": failures} if failures else {}),
        **extra,
        **_provenance(),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
