"""North-star benchmark: AL pool-scoring wall-clock per iteration.

Measures the fused TPU scoring graph at BASELINE.json configs[4] scale —
16-member committee over a 100k-excerpt synthetic pool — against a CPU
baseline with the reference's structure (``amg_test.py:428-447``): a Python
loop over members, per-frame ``predict_proba``, per-song groupby-mean, then
``np.mean`` → ``scipy.stats.entropy`` → ``argsort`` top-q on host.

The device path runs the identical math as ONE jit'd XLA graph: batched
member probabilities (a single MXU matmul for all members), frame→song mean,
consensus mean, entropy, and top-k fused, pool axis sharded across all
available chips.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
``vs_baseline`` is the CPU-over-device speedup (higher is better; the
BASELINE.json north star is >= 50x).
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_inputs(n_members: int, n_pool: int, n_frames: int, n_features: int,
                n_class: int, seed: int = 1987):
    """Synthetic pool features + linear committee members.

    Frame features mirror the AMG openSMILE layout (260-d per-second frames,
    several frames per song — ``amg_test.py:64,435-437``); members are
    softmax-linear probabilistic classifiers (the SGD-logistic committee
    member's functional form).
    """
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n_pool, n_frames, n_features), np.float32)
    w = (rng.standard_normal((n_members, n_features, n_class), np.float32)
         / np.sqrt(n_features))
    b = rng.standard_normal((n_members, n_class), np.float32) * 0.1
    return x, w, b


def cpu_reference_iteration(x, w, b, k: int):
    """Reference-structure scoring on host: per-member Python loop
    (``amg_test.py:428-438``), then consensus mean → scipy entropy → argsort
    top-q (``amg_test.py:441-447``)."""
    from scipy.stats import entropy as scipy_entropy

    n_pool, n_frames, n_features = x.shape
    frames = x.reshape(n_pool * n_frames, n_features)
    pred_prob = []
    for m in range(w.shape[0]):  # sequential member loop, as the reference
        logits = frames @ w[m] + b[m]
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(axis=1, keepdims=True)
        # groupby('s_id').mean() — frames are contiguous per song here.
        pred_prob.append(p.reshape(n_pool, n_frames, -1).mean(axis=1))
    consensus = np.mean(np.asarray(pred_prob), axis=0)
    ent = scipy_entropy(consensus, axis=1)
    q_idx = np.argsort(ent)[::-1][:k]
    return ent, q_idx


def build_device_iteration(k: int):
    """The fused graph: members' probs → song mean → consensus → entropy →
    top-k, one XLA program, pool axis sharded across all devices.

    The extra ``eps`` argument (folded in as ``+ eps * 0.0``, a no-op) lets
    the timing loop chain iterations through a device-side data dependency,
    so steady-state per-iteration latency is measured without a host sync
    per call (on this environment's tunneled TPU, ``block_until_ready`` does
    not block and a host readback costs ~90 ms of tunnel overhead that a real
    AL loop consuming device-resident results never pays).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from consensus_entropy_tpu.ops.scoring import score_mc
    from consensus_entropy_tpu.parallel.mesh import POOL_AXIS, make_pool_mesh

    mesh = make_pool_mesh()

    def iteration(x, w, b, mask, eps):
        logits = jnp.einsum("nkf,mfc->mnkc", x, w + eps * 0.0)
        logits = logits + b[:, None, None, :]
        probs = jax.nn.softmax(logits, axis=-1)
        song_probs = jnp.mean(probs, axis=2)  # groupby(s_id).mean() parity
        return score_mc(song_probs, mask, k=k)

    x_sh = NamedSharding(mesh, P(POOL_AXIS))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(iteration,
                 in_shardings=(x_sh, repl, repl, x_sh, repl),
                 out_shardings=repl)
    return mesh, x_sh, fn


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--members", type=int, default=16)
    ap.add_argument("--pool", type=int, default=100_000)
    ap.add_argument("--frames", type=int, default=4)
    ap.add_argument("--features", type=int, default=260)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--chain", type=int, default=50,
                    help="iterations per dependent-chain timing window")
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--cpu-reps", type=int, default=3)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    x, w, b = make_inputs(args.members, args.pool, args.frames,
                          args.features, args.classes)
    _log(f"devices: {jax.devices()}")
    _log(f"pool {args.pool} x {args.frames} frames x {args.features} feats, "
         f"{args.members} members, k={args.k}")

    # -- device path ------------------------------------------------------
    mesh, x_sh, fn = build_device_iteration(args.k)
    # Pad the pool axis to a multiple of the mesh (fixed-shape contract).
    n_dev = mesh.devices.size
    n_pad = -(-args.pool // n_dev) * n_dev
    x_pad = np.zeros((n_pad,) + x.shape[1:], np.float32)
    x_pad[: args.pool] = x
    mask = np.zeros(n_pad, bool)
    mask[: args.pool] = True

    xd = jax.device_put(x_pad, x_sh)
    wd, bd = jnp.asarray(w), jnp.asarray(b)
    md = jax.device_put(mask, x_sh)

    t0 = time.perf_counter()
    eps = jnp.float32(0.0)
    for _ in range(3):  # compile + fully execute before timing
        result = fn(xd, wd, bd, md, eps)
        eps = result.values[0]
    np.asarray(result.values)
    _log(f"compile + warmup: {time.perf_counter() - t0:.2f}s")

    times = []
    for _ in range(args.trials):
        t0 = time.perf_counter()
        eps = jnp.float32(0.0)
        for _ in range(args.chain):
            result = fn(xd, wd, bd, md, eps)
            eps = result.values[0]  # device-side dependency between iters
        np.asarray(result.values)  # one sync per chain
        times.append((time.perf_counter() - t0) / args.chain)
    dev_ms = float(np.median(times) * 1e3)
    _log(f"device median over {args.trials} x {args.chain}-iter chains: "
         f"{dev_ms:.3f} ms/iter (min {min(times)*1e3:.3f})")

    # -- CPU reference-structure baseline ---------------------------------
    cpu_times = []
    for _ in range(args.cpu_reps):
        t0 = time.perf_counter()
        ent_cpu, idx_cpu = cpu_reference_iteration(x, w, b, args.k)
        cpu_times.append(time.perf_counter() - t0)
    cpu_ms = float(np.median(cpu_times) * 1e3)
    _log(f"cpu median over {args.cpu_reps} reps: {cpu_ms:.1f} ms")

    # -- parity check -----------------------------------------------------
    ent_dev = np.asarray(result.entropy)[: args.pool]
    max_err = float(np.max(np.abs(ent_dev - ent_cpu)))
    same_queries = set(np.asarray(result.indices).tolist()) == set(
        idx_cpu.tolist())
    _log(f"entropy max |err| vs scipy: {max_err:.2e}; "
         f"top-{args.k} sets match: {same_queries}")
    if max_err > 1e-3 or not same_queries:
        _log("PARITY FAILURE — benchmark numbers not comparable")
        return 1

    print(json.dumps({
        "metric": f"al_pool_scoring_latency_{args.members}m_{args.pool}",
        "value": round(dev_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / dev_ms, 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
